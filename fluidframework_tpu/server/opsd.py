"""Live operations plane (ISSUE 17): scrape endpoint, latency
attribution, hot-doc introspection.

Until now every observability surface was post-hoc — ``full_snapshot()``
embedded in bench records, ``TimeSeriesStore`` ticked only inside
bench.py, ``tools/healthz.py`` reading JSONL exports after the run. This
module makes a *running* server observable:

* :class:`OpsServer` — a threaded HTTP façade (``utils.ops_http``) over
  the process singletons: ``/metrics`` (Prometheus text exposition with
  correct content-type and label escaping), ``/healthz`` (live SLO
  scorecard JSON), ``/debug/flights`` (flight-recorder ring),
  ``/debug/trace`` (recent spans as Chrome trace-event JSON),
  ``/debug/hotdocs`` (heavy-hitter sketch), ``/debug/latency``
  (per-stage breakdown). A background ticker thread finally runs
  ``TimeSeriesStore`` sampling + ``SLOEngine`` burn checks on live
  servers, the role the reference's Prometheus scrape loop plays behind
  Routerlicious.

* Latency attribution — :func:`observe_window_timeline` turns the
  monotonic crossing stamps the ingress door and the ingest executor
  record onto each window (rx-buffer → drain/decode → admission → pack →
  sequence → dispatch → durable-append → ack) into per-stage
  ``stage_*_ms`` histograms. Stages are *consecutive timeline segments*,
  so they sum to the observed end-to-end ack latency by construction —
  :func:`latency_breakdown` is the "which stage do we shard next" view.

* :class:`SpaceSaving` — the bounded heavy-hitter sketch over
  ``(doc, tenant)`` maintained in the drain pass; ``/debug/hotdocs`` and
  the ``hotdoc_*`` gauges expose the routing/eviction signal ROADMAP
  items 1 and 3 consume.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import capacity as _capacity
from ..utils import flight_recorder as _flight
from ..utils import slo as _slo
from ..utils import tracing as _tracing
from ..utils.ops_http import OpsHTTPServer, json_body
from ..utils.telemetry import (PROM_CONTENT_TYPE, REGISTRY,
                               MetricsRegistry)
from ..utils.timeseries import TimeSeriesStore

__all__ = ["OpsServer", "SpaceSaving", "STAGES",
           "observe_window_timeline", "latency_breakdown"]


# --------------------------------------------------------------------------
# latency attribution
# --------------------------------------------------------------------------

#: canonical stage order of the ingest path; ``stage_{name}_ms``
#: histograms are consecutive segments of one monotonic timeline
STAGES = ("rx", "decode", "admit", "pack",
          "sequence", "dispatch", "log", "ack")


def observe_window_timeline(tl: dict, marks: dict, t_ack: float,
                            registry: Optional[MetricsRegistry] = None,
                            exemplar: Any = None) -> None:
    """Attribute one window's end-to-end ack latency to stages.

    ``tl`` is the front-door timeline the drain pass stamps
    (``t_rx``/``t_drain0``/``decode_ms``/``admit_ms``/``t_ready``),
    ``marks`` the executor-side crossings the engine's stage methods
    stamp (``pack1``/``seq1``/``disp1``/``log1``, absolute
    ``perf_counter`` seconds), ``t_ack`` the ack-fan time. Segment k is
    ``crossing[k+1] - crossing[k]`` with crossings clamped monotonic, so
    ``sum(stage_*_ms) == stage_e2e_ack_ms`` exactly — queue waits land
    in the stage that absorbed them (pack's segment includes the
    executor hand-off wait; ack's the done-callback bounce)."""
    t_rx = float(tl["t_rx"])
    t_ready = float(tl["t_ready"])
    admit_s = max(0.0, float(tl.get("admit_ms", 0.0))) * 1e-3
    crossings = [
        t_rx,
        float(tl["t_drain0"]),      # rx segment ends: drain pass starts
        t_ready - admit_s,          # decode ends where admission begins
        t_ready,                    # decoded + admitted, awaiting submit
        float(marks.get("pack1", t_ready)),
        float(marks.get("seq1", t_ready)),
        float(marks.get("disp1", t_ready)),
        float(marks.get("log1", t_ready)),
        float(t_ack),
    ]
    for i in range(1, len(crossings)):   # clock skew / missing marks
        if crossings[i] < crossings[i - 1]:
            crossings[i] = crossings[i - 1]
    reg = registry if registry is not None else REGISTRY
    for name, a, b in zip(STAGES, crossings, crossings[1:]):
        reg.observe(f"stage_{name}_ms", (b - a) * 1e3)
    reg.observe("stage_e2e_ack_ms", (crossings[-1] - crossings[0]) * 1e3,
                exemplar=exemplar)


def latency_breakdown(registry: Optional[MetricsRegistry] = None) -> dict:
    """Per-stage summary of the accumulated attribution histograms.

    ``stage_sum_ms`` (the sum of per-stage means) matches ``e2e_mean_ms``
    within clock-granularity tolerance whenever every observed window
    recorded all stages — the acceptance check for ISSUE 17 and the
    sharding signal: the stage with the largest mean share is the next
    thing to scale out."""
    reg = registry if registry is not None else REGISTRY
    stages: Dict[str, dict] = {}
    stage_sum = 0.0
    for name in STAGES:
        h = reg.histograms.get(f"stage_{name}_ms")
        if h is None or h.n == 0:
            continue
        stages[name] = {"mean_ms": h.mean, "p50_ms": h.percentile(50),
                        "p99_ms": h.percentile(99), "count": h.n}
        stage_sum += h.mean
    e2e = reg.histograms.get("stage_e2e_ack_ms")
    e2e_mean = e2e.mean if e2e is not None and e2e.n else 0.0
    for name, row in stages.items():
        row["share"] = row["mean_ms"] / e2e_mean if e2e_mean else 0.0
    return {
        "stages": stages,
        "stage_sum_ms": stage_sum,
        "e2e_mean_ms": e2e_mean,
        "e2e_p99_ms": e2e.percentile(99) if e2e is not None else 0.0,
        "windows": e2e.n if e2e is not None else 0,
        "coverage": stage_sum / e2e_mean if e2e_mean else 0.0,
    }


# --------------------------------------------------------------------------
# heavy-hitter sketch
# --------------------------------------------------------------------------

class SpaceSaving:
    """Bounded Space-Saving heavy-hitter sketch (Metwally et al. 2005).

    Tracks at most ``capacity`` keys in O(capacity) memory. Estimated
    counts overestimate the true count by at most the entry's ``err``
    (the evicted minimum it inherited), and any key whose true count
    exceeds ``total / capacity`` is guaranteed to be tracked — exactly
    the guarantee a hot-doc router or eviction policy needs. Thread-safe:
    the drain pass offers from the ingress loop, the ops endpoint reads
    from scrape threads."""

    def __init__(self, capacity: int = 256):
        self.capacity = max(1, int(capacity))
        #: key -> [count, err]
        self._entries: Dict[Any, List[int]] = {}
        self.total = 0
        self._lock = threading.Lock()

    def offer(self, key: Any, n: int = 1) -> None:
        with self._lock:
            self.total += n
            e = self._entries.get(key)
            if e is not None:
                e[0] += n
                return
            if len(self._entries) < self.capacity:
                self._entries[key] = [n, 0]
                return
            # evict the current minimum; the newcomer inherits its count
            # as the overestimation bound
            victim = min(self._entries, key=lambda k: self._entries[k][0])
            floor = self._entries.pop(victim)[0]
            self._entries[key] = [floor + n, floor]

    def top(self, k: int = 10) -> List[Tuple[Any, int, int]]:
        """``(key, estimated_count, err)`` rows, largest first.
        ``estimated_count - err`` is a guaranteed lower bound."""
        with self._lock:
            rows = sorted(self._entries.items(),
                          key=lambda kv: kv[1][0], reverse=True)
        return [(key, e[0], e[1]) for key, e in rows[:k]]

    def __len__(self) -> int:
        return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.total = 0


def publish_hotdoc_gauges(sketches: List[SpaceSaving],
                          registry: Optional[MetricsRegistry] = None
                          ) -> None:
    """Roll the attached sketches up into the ``hotdoc_*`` gauges: how
    many keys are tracked, the hottest key's estimated ops, and its
    share of all sketched traffic — the skew signal at a glance."""
    reg = registry if registry is not None else REGISTRY
    tracked = sum(len(s) for s in sketches)
    total = sum(s.total for s in sketches)
    top = 0
    for s in sketches:
        rows = s.top(1)
        if rows:
            top = max(top, rows[0][1])
    reg.set_gauge("hotdoc_tracked", float(tracked))
    reg.set_gauge("hotdoc_top_count", float(top))
    reg.set_gauge("hotdoc_top_share", top / total if total else 0.0)


# --------------------------------------------------------------------------
# JSON hygiene
# --------------------------------------------------------------------------

def _finite(obj: Any) -> Any:
    """Recursively replace non-finite floats with ``None`` so route
    payloads stay strict JSON (scorecard burn rates are ``inf`` when a
    window has no samples; histogram percentiles can be ``inf``)."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _finite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_finite(v) for v in obj]
    return obj


# --------------------------------------------------------------------------
# the ops server
# --------------------------------------------------------------------------

class OpsServer:
    """The live operations plane of one process.

    Attach it to anything that serves: ``LocalService.start_ops()``,
    ``ColumnarAlfred.start_ops()``, ``AlfredServer.start_ops()``, or the
    tools' ``--ops-port``. It owns (or borrows) a ``TimeSeriesStore`` +
    ``SLOEngine`` pair and a background ticker thread so sampling and
    burn-rate checks run continuously — ``tick_interval_s=0`` disables
    the ticker for hosts that already tick their own control loop
    (tenant_sim)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 registry: Optional[MetricsRegistry] = None,
                 store: Optional[TimeSeriesStore] = None,
                 slo_engine: Optional[Any] = None,
                 specs: Optional[list] = None,
                 recorder: Optional[Any] = None,
                 tracer: Optional[Any] = None,
                 tick_interval_s: float = 1.0):
        self.registry = registry if registry is not None else REGISTRY
        self.store = store if store is not None \
            else TimeSeriesStore(registry=self.registry)
        if slo_engine is not None:
            self.slo_engine = slo_engine
        else:
            self.slo_engine = _slo.SLOEngine(
                self.store, specs=specs if specs is not None
                else _slo.default_slos(), registry=self.registry)
        self.recorder = recorder if recorder is not None \
            else _flight.RECORDER
        self.tracer = tracer if tracer is not None else _tracing.TRACER
        self.tick_interval_s = tick_interval_s
        self.ticks = 0
        self._t_started = time.time()
        self._sketches: List[SpaceSaving] = []
        self._partition_providers: List[Callable[[], List[dict]]] = []
        self._reader_hubs: List[Any] = []
        self._on_tick: List[Callable[[], None]] = []
        self._tick_stop = threading.Event()
        self._ticker: Optional[threading.Thread] = None
        self.http = (OpsHTTPServer(host, port)
                     .route("/metrics", self._r_metrics)
                     .route("/healthz", self._r_healthz)
                     .route("/debug/flights", self._r_flights)
                     .route("/debug/trace", self._r_trace)
                     .route("/debug/hotdocs", self._r_hotdocs)
                     .route("/debug/latency", self._r_latency)
                     .route("/debug/partitions", self._r_partitions)
                     .route("/debug/memory", self._r_memory)
                     .route("/debug/docs", self._r_docs)
                     .route("/debug/readers", self._r_readers))

    # -------------------------------------------------------- attachments

    def add_hotdocs(self, sketch: SpaceSaving) -> "OpsServer":
        """Expose a drain-pass sketch at ``/debug/hotdocs`` and in the
        ``hotdoc_*`` gauges (multiple doors may each attach one)."""
        self._sketches.append(sketch)
        return self

    def add_partitions(self, provider: Callable[[], List[dict]]
                       ) -> "OpsServer":
        """Expose a partitioned door's per-partition rows (occupancy,
        backlog, resident docs — ISSUE 18) at ``/debug/partitions``."""
        self._partition_providers.append(provider)
        return self

    def add_readers(self, hub: Any) -> "OpsServer":
        """Expose an observer hub's per-subscriber rows (window lag,
        delivered volume, shed counts — ISSUE 20) at ``/debug/readers``.
        ``hub`` is anything with ``.readers()`` and ``.stats()``
        (``server.observer.ObserverHub``); multiple doors may each
        attach their own."""
        self._reader_hubs.append(hub)
        return self

    def on_tick(self, fn: Callable[[], None]) -> "OpsServer":
        """Run ``fn()`` on every ticker beat (host gauge publishers —
        e.g. a service exporting replica queue depth). Exceptions are
        swallowed: a bad publisher must not kill sampling."""
        self._on_tick.append(fn)
        return self

    # ------------------------------------------------------------- routes

    def _r_metrics(self, _q: Dict[str, str]) -> Tuple[str, bytes]:
        self.registry.inc("ops_scrapes_total")
        text = self.registry.render_prometheus()
        return (PROM_CONTENT_TYPE, text.encode("utf-8"))

    def _r_healthz(self, _q: Dict[str, str]) -> Tuple[str, bytes]:
        rows = self.slo_engine.scorecard()
        judged = [r for r in rows if r.get("judged")]
        return json_body(_finite({
            "ok": all(r["ok"] for r in judged),
            "judged": len(judged),
            "ticks": self.ticks,
            "uptime_s": time.time() - self._t_started,
            "rows": rows,
        }))

    def _r_flights(self, q: Dict[str, str]) -> Tuple[str, bytes]:
        limit = int(q.get("n", "512"))
        events = self.recorder.snapshot()
        return json_body(_finite({
            "count": len(events),
            "suppressed": dict(self.recorder.suppressed),
            "events": events[-limit:],
        }))

    def _r_trace(self, q: Dict[str, str]) -> Tuple[str, bytes]:
        if q.get("list"):
            return json_body({"trace_ids": self.tracer.trace_ids()})
        limit = int(q.get("n", "2048"))
        events = self.tracer.events(q.get("trace"))[-limit:]
        return json_body(_finite(
            {"traceEvents": [_tracing.chrome_event(e) for e in events]}))

    def _r_hotdocs(self, q: Dict[str, str]) -> Tuple[str, bytes]:
        k = int(q.get("k", "20"))
        merged: List[Tuple[Any, int, int]] = []
        for s in self._sketches:
            merged.extend(s.top(k))
        merged.sort(key=lambda row: row[1], reverse=True)
        return json_body(_finite({
            "capacity": sum(s.capacity for s in self._sketches),
            "tracked": sum(len(s) for s in self._sketches),
            "total_ops": sum(s.total for s in self._sketches),
            "top": [{"doc": key[0], "tenant": key[1],
                     "count": count, "err": err}
                    if isinstance(key, tuple) and len(key) == 2 else
                    {"key": key, "count": count, "err": err}
                    for key, count, err in merged[:k]],
        }))

    def _r_latency(self, q: Dict[str, str]) -> Tuple[str, bytes]:
        part = q.get("partition")
        if part is not None:
            # the partition dimension (ISSUE 18): the door observes the
            # stage timeline a second time into a partition-labeled
            # collector — serve THAT collector's breakdown
            suffix = "{partition=%s}" % part
            for key, reg in self.registry.components().items():
                if key.endswith(suffix) and any(
                        n.startswith("stage_") for n in reg.histograms):
                    out = latency_breakdown(reg)
                    out["partition"] = int(part)
                    return json_body(_finite(out))
            return json_body(_finite({"partition": int(part),
                                      "stages": {}, "windows": 0}))
        return json_body(_finite(latency_breakdown(self.registry)))

    def _r_partitions(self, _q: Dict[str, str]) -> Tuple[str, bytes]:
        rows: List[dict] = []
        for provider in self._partition_providers:
            try:
                rows.extend(provider())
            except Exception as e:   # debug route: never 500 the plane
                rows.append({"error": repr(e)})
        return json_body(_finite({"count": len(rows),
                                  "partitions": rows}))

    def _r_readers(self, _q: Dict[str, str]) -> Tuple[str, bytes]:
        """Read-plane census (ISSUE 20): per-subscriber lag/shed rows
        from every attached observer hub plus the fleet aggregate."""
        rows: List[dict] = []
        agg = {"subscribers": 0, "windows_published": 0,
               "ops_published": 0, "worst_lag_windows": 0,
               "sheds": 0, "parked": 0, "staleness_p99_s": 0.0}
        for hub in self._reader_hubs:
            try:
                rows.extend(hub.readers())
                s = hub.stats()
            except Exception as e:   # debug route: never 500 the plane
                rows.append({"error": repr(e)})
                continue
            for k in ("subscribers", "windows_published",
                      "ops_published", "sheds", "parked"):
                agg[k] += s.get(k, 0)
            agg["worst_lag_windows"] = max(
                agg["worst_lag_windows"], s.get("worst_lag_windows", 0))
            agg["staleness_p99_s"] = max(
                agg["staleness_p99_s"], s.get("staleness_p99_s", 0.0))
        return json_body(_finite({**agg, "count": len(rows),
                                  "readers": rows}))

    def _r_memory(self, q: Dict[str, str]) -> Tuple[str, bytes]:
        """Capacity census (ISSUE 19): host planes by owner/category,
        device buffers by engine, compile-cache stats, budget headroom.
        ``?device=0`` skips the live-array walk; ``?k=N`` sizes the
        heaviest/coldest lists."""
        try:
            census = _capacity.LEDGER.census(
                top_k=int(q.get("k", "8")),
                device=q.get("device", "1") not in ("0", "false"),
                device_ttl_s=5.0)
        except Exception as e:   # debug route: never 500 the plane
            census = {"error": repr(e)}
        return json_body(_finite(census))

    def _r_docs(self, q: Dict[str, str]) -> Tuple[str, bytes]:
        """Doc-level residency view: resident counts by owner, top-K
        heaviest docs, top-K coldest (exact last-touch stamps)."""
        try:
            census = _capacity.LEDGER.census(
                top_k=int(q.get("k", "16")), device=False)
            out = {"docs": census["docs"], "idle": census["idle"],
                   "heaviest": census["top"]["heaviest"],
                   "coldest": census["top"]["coldest"]}
        except Exception as e:
            out = {"error": repr(e)}
        return json_body(_finite(out))

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "OpsServer":
        self.http.start()
        if self.tick_interval_s and self._ticker is None:
            self._tick_stop.clear()
            self._ticker = threading.Thread(
                target=self._tick_loop, name="opsd-ticker", daemon=True)
            self._ticker.start()
        return self

    def stop(self) -> None:
        self._tick_stop.set()
        ticker = self._ticker
        self._ticker = None
        if ticker is not None:
            ticker.join(timeout=5)
        self.http.stop()

    @property
    def port(self) -> int:
        return self.http.port

    @property
    def url(self) -> str:
        return self.http.url

    def __enter__(self) -> "OpsServer":
        return self.start()

    def __exit__(self, *_exc) -> None:
        self.stop()

    # -------------------------------------------------------------- ticker

    def tick_once(self, now: Optional[float] = None) -> None:
        """One sampling beat: time-series sample, SLO burn check, hot-doc
        gauges, host publishers. The ticker thread calls this; hosts
        with their own control loop may call it directly."""
        self.ticks += 1
        self.registry.inc("ops_ticks_total")
        self.registry.set_gauge("ops_ticker_last_unix", time.time())
        self.registry.set_gauge("ops_uptime_s",
                                time.time() - self._t_started)
        if self._sketches:
            publish_hotdoc_gauges(self._sketches, self.registry)
        for fn in list(self._on_tick):
            try:
                fn()
            except Exception:
                pass
        # capacity gauges BEFORE the SLO check so memory_budget_headroom
        # is judged against this beat's census (device walk TTL-cached —
        # the 1 Hz ticker stays within the scrape-overhead bound)
        try:
            _capacity.LEDGER.publish_gauges(self.registry,
                                            device_ttl_s=5.0)
        except Exception:
            pass
        self.store.tick(now=now)
        try:
            self.slo_engine.check(now=now)
        except Exception:
            pass

    def _tick_loop(self) -> None:
        while not self._tick_stop.wait(self.tick_interval_s):
            self.tick_once()
