"""Overload protection: closed-loop admission control for both doors.

Reference counterpart: Routerlicious' per-tenant throttling in front of
Alfred (SURVEY.md §1 — the reference service rate-limits ops per tenant
before they reach the Kafka→Deli pipeline and answers over-budget
clients with a retryAfter). Here the same role sits in the drain pass of
BOTH front doors (``server.ingress``, ``server.columnar_ingress``):
every decoded op batch is offered to one :class:`AdmissionController`
*before* it reaches the sequencer / ``PipelinedIngestExecutor``, and
whatever is not admitted is answered with an explicit ``throttled``
frame carrying ``retry_after_ms`` — shed work is never silently dropped
and never burns a clientSeq (it is refused before the sequencer ever
sees the number, so the client resubmits the SAME cseq after backoff).

Three mechanisms, composable and individually optional:

- **per-tenant token buckets** — each tenant declares a budget
  (ops/sec + burst); a batch consumes tokens for its admitted PREFIX
  only. Prefix (suffix-shed) semantics matter: the sequencer nacks
  clientSeq gaps, so once op ``k`` of a batch is shed everything after
  it must shed too — the doors enforce the same rule across batches
  with a shed fence. Optional per-doc buckets bound any single
  document's share the same way.
- **concurrency limit + deadline shedding** — a batch that would land
  on a backlog past ``max_inflight_ops`` is shed outright, and when a
  deadline budget is configured (or the op carries one), a batch whose
  *estimated* sequencing delay (backlog ÷ EWMA service rate, fed by
  :meth:`note_served`) already exceeds it is shed at admission instead
  of wasted in the engine.
- **pressure shedding** — a probabilistic shed gate plus a global
  budget *scale* multiplier, both driven by :class:`ControlPolicy`:
  an AIMD loop over the existing ``SLOEngine`` fast/slow burn-rate
  windows that halves budgets / steps shed probability up while an
  objective is burning and additively recovers when it stops.

Every decision is counted (``admission_*`` — docs/OBSERVABILITY.md) so
healthz and the tenant simulator can see who was shed, why, and how the
control loop moved.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Hashable, Optional

from ..utils.telemetry import REGISTRY

#: floor on any retry hint — a 0ms hint would have clients hammering
_MIN_RETRY_MS = 5.0
#: ceiling on any retry hint — bounded client-side pause per episode
_MAX_RETRY_MS = 2000.0


class TokenBucket:
    """Classic token bucket with prefix-grant semantics: :meth:`grant`
    admits the largest prefix of ``n`` requested ops the current tokens
    cover (never a mid-batch subset — the doors shed suffixes only).
    ``scale`` multiplies the refill rate AND the burst ceiling, the
    knob :class:`ControlPolicy` turns per tenant without rebuilding
    buckets."""

    __slots__ = ("rate", "burst", "tokens", "_t")

    def __init__(self, rate: float, burst: Optional[float] = None):
        if rate <= 0:
            raise ValueError(f"rate must be positive, got {rate}")
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None else float(rate)
        self.tokens = self.burst
        self._t: Optional[float] = None

    def _refill(self, now: float, scale: float) -> None:
        if self._t is not None and now > self._t:
            self.tokens = min(self.burst * scale,
                              self.tokens + self.rate * scale *
                              (now - self._t))
        self._t = now

    def grant(self, n: int, now: float, scale: float = 1.0) -> int:
        """Admit the largest prefix of ``n`` ops covered by the current
        tokens; consumes exactly what it grants."""
        self._refill(now, scale)
        k = min(n, int(self.tokens))
        if k > 0:
            self.tokens -= k
        return k

    def retry_after_ms(self, n: int, now: float,
                       scale: float = 1.0) -> float:
        """Milliseconds until ``n`` tokens will have accumulated —
        pure query, consumes nothing."""
        self._refill(now, scale)
        deficit = n - self.tokens
        if deficit <= 0:
            return _MIN_RETRY_MS
        return min(_MAX_RETRY_MS, max(
            _MIN_RETRY_MS, deficit / (self.rate * scale) * 1000.0))


@dataclass
class Admission:
    """One :meth:`AdmissionController.admit` verdict: the admitted
    PREFIX length, the retry hint for the shed suffix, and why."""

    admitted: int
    retry_after_ms: float = 0.0
    reason: str = "ok"       # ok | budget | doc_budget | deadline |
    #                          inflight | pressure


class AdmissionController:
    """Per-tenant/per-doc token-bucket + concurrency-limit admission.

    ``tenants``            {name: ops_per_sec} declared budgets; a
                           client bound to an unknown/absent tenant is
                           governed by ``default_rate`` (None = no
                           budget, admission limited only by the other
                           gates).
    ``default_rate``       ops/sec bucket auto-created per tenant on
                           first sight when set.
    ``max_inflight_ops``   shed a batch whose backlog-at-admission
                           exceeds this (0 = unlimited).
    ``deadline_ms``        default ingress deadline budget per op; a
                           batch is shed when the EWMA-estimated
                           sequencing delay already exceeds it (0 =
                           disabled; ops may carry their own).
    ``rng``                seeded source for the probabilistic shed
                           gate (deterministic sims).

    Thread-safe: each door's event loop and the policy ticker share one
    controller under a single lock.
    """

    def __init__(self, tenants: Optional[Dict[str, float]] = None,
                 default_rate: Optional[float] = None,
                 burst_factor: float = 1.0,
                 max_inflight_ops: int = 0,
                 deadline_ms: float = 0.0,
                 rng: Optional[random.Random] = None,
                 clock=time.monotonic,
                 registry=None):
        self._lock = threading.Lock()
        self.clock = clock
        self.default_rate = default_rate
        self.burst_factor = burst_factor
        self.max_inflight_ops = max_inflight_ops
        self.deadline_ms = deadline_ms
        self.rng = rng or random
        self.registry = registry if registry is not None else REGISTRY
        self._tenant_bucket: Dict[str, TokenBucket] = {}
        self._doc_bucket: Dict[Hashable, TokenBucket] = {}
        self._tenant_of: Dict[Any, str] = {}
        #: policy knobs (ControlPolicy writes, admit reads)
        self.scale = 1.0
        self.shed_probability = 0.0
        #: EWMA served ops/sec (deadline estimation); None until fed
        self._service_rate: Optional[float] = None
        self._served_t: Optional[float] = None
        self.admitted_total = 0
        self.shed_total = 0
        self._tenant_stats: Dict[str, Dict[str, int]] = {}
        for name, rate in (tenants or {}).items():
            self.register_tenant(name, rate)

    # ---------------------------------------------------------- registration

    def register_tenant(self, name: str, rate: float,
                        burst: Optional[float] = None) -> None:
        """Declare (or re-declare) a tenant's ops/sec budget."""
        with self._lock:
            self._tenant_bucket[name] = TokenBucket(
                rate, burst if burst is not None
                else rate * self.burst_factor)
            self._tenant_stats.setdefault(
                name, {"admitted": 0, "shed": 0})

    def set_doc_rate(self, doc: Hashable, rate: float,
                     burst: Optional[float] = None) -> None:
        """Bound one document's share with its own bucket."""
        with self._lock:
            self._doc_bucket[doc] = TokenBucket(
                rate, burst if burst is not None
                else rate * self.burst_factor)

    def bind(self, client_id: Any, tenant: Optional[str] = None) -> str:
        """Bind a client identity to a tenant (join/connect time). A
        ``None`` tenant keeps any existing binding, else falls back to
        a per-client default tenant name."""
        with self._lock:
            if tenant is None:
                tenant = self._tenant_of.get(client_id,
                                             f"client-{client_id}")
            self._tenant_of[client_id] = tenant
            if tenant not in self._tenant_bucket \
                    and self.default_rate is not None:
                self._tenant_bucket[tenant] = TokenBucket(
                    self.default_rate,
                    self.default_rate * self.burst_factor)
            self._tenant_stats.setdefault(
                tenant, {"admitted": 0, "shed": 0})
            return tenant

    def tenant_of(self, client_id: Any) -> str:
        with self._lock:
            return self._tenant_of.get(client_id, f"client-{client_id}")

    # --------------------------------------------------------------- control

    def set_pressure(self, scale: Optional[float] = None,
                     shed_probability: Optional[float] = None) -> None:
        """Policy knobs: global budget multiplier + probabilistic shed
        gate. Gauges track both so healthz shows the loop moving."""
        with self._lock:
            if scale is not None:
                self.scale = max(0.0, min(1.0, scale))
            if shed_probability is not None:
                self.shed_probability = max(0.0, min(1.0,
                                                     shed_probability))
            self.registry.set_gauge("admission_budget_scale", self.scale)
            self.registry.set_gauge("admission_shed_probability",
                                    self.shed_probability)

    def note_served(self, n: int, now: Optional[float] = None) -> None:
        """Feed the EWMA service-rate estimator: ``n`` ops finished
        sequencing (ack fan-out time). Powers deadline shedding."""
        if n <= 0:
            return
        now = self.clock() if now is None else now
        with self._lock:
            if self._served_t is not None:
                dt = now - self._served_t
                if dt > 1e-6:
                    inst = n / dt
                    self._service_rate = inst if self._service_rate \
                        is None else (0.8 * self._service_rate
                                      + 0.2 * inst)
            self._served_t = now

    def estimated_delay_ms(self, backlog: int) -> float:
        """Expected sequencing delay for an op landing behind
        ``backlog`` queued ops, from the EWMA service rate. 0 until
        the estimator has been fed (absence of evidence never sheds)."""
        rate = self._service_rate
        if not rate or backlog <= 0:
            return 0.0
        return backlog / rate * 1000.0

    # -------------------------------------------------------------- admission

    def admit(self, client_id: Any, doc: Hashable, n: int,
              backlog: int = 0, now: Optional[float] = None,
              deadline_ms: Optional[float] = None) -> Admission:
        """Offer a batch of ``n`` ops from ``client_id`` on ``doc``.
        Returns the admitted prefix length plus a retry hint for the
        shed suffix. Order of gates: deadline (the work is already
        late), concurrency (the pipeline is already full), pressure
        (the control loop said brake), then the token buckets."""
        if n <= 0:
            return Admission(0, _MIN_RETRY_MS, "ok")
        now = self.clock() if now is None else now
        with self._lock:
            tenant = self._tenant_of.get(client_id,
                                         f"client-{client_id}")
            budget = deadline_ms if deadline_ms is not None \
                else self.deadline_ms
            if budget and self._estimate_locked(backlog) > budget:
                return self._shed_locked(tenant, n, "deadline",
                                         self._retry_locked(tenant, doc,
                                                            n, now))
            if self.max_inflight_ops and backlog > self.max_inflight_ops:
                return self._shed_locked(
                    tenant, n, "inflight",
                    self._retry_locked(tenant, doc, n, now))
            if self.shed_probability > 0.0 \
                    and self.rng.random() < self.shed_probability:
                return self._shed_locked(
                    tenant, n, "pressure",
                    self._retry_locked(tenant, doc, n, now))
            k = n
            reason = "ok"
            tb = self._tenant_bucket.get(tenant)
            if tb is not None:
                k = tb.grant(n, now, self.scale)
                if k < n:
                    reason = "budget"
            db = self._doc_bucket.get(doc)
            if db is not None and k > 0:
                kd = db.grant(k, now, self.scale)
                if kd < k:
                    # over-granted tenant tokens for the doc-shed tail:
                    # refund so the tenant is not double-charged
                    if tb is not None:
                        tb.tokens += k - kd
                    k, reason = kd, "doc_budget"
            self.admitted_total += k
            st = self._tenant_stats.setdefault(
                tenant, {"admitted": 0, "shed": 0})
            st["admitted"] += k
            if k > 0:
                self.registry.inc("admission_admitted_total", k)
            if k < n:
                shed = n - k
                self.shed_total += shed
                st["shed"] += shed
                self.registry.inc("admission_shed_total", shed)
                self.registry.inc(f"admission_shed_{reason}_total", shed)
                return Admission(k, self._retry_locked(tenant, doc,
                                                       n - k, now),
                                 reason)
            return Admission(k, 0.0, "ok")

    def retry_after_ms(self, client_id: Any, doc: Hashable = None,
                       n: int = 1, now: Optional[float] = None) -> float:
        """Pure retry hint for ``n`` ops (consumes nothing) — the
        doors use it for fence-blocked batches that were never offered
        to the buckets."""
        now = self.clock() if now is None else now
        with self._lock:
            return self._retry_locked(
                self._tenant_of.get(client_id, f"client-{client_id}"),
                doc, n, now)

    def _retry_locked(self, tenant: str, doc: Hashable, n: int,
                      now: float) -> float:
        hint = _MIN_RETRY_MS
        tb = self._tenant_bucket.get(tenant)
        if tb is not None:
            hint = max(hint, tb.retry_after_ms(n, now, self.scale))
        db = self._doc_bucket.get(doc)
        if db is not None:
            hint = max(hint, db.retry_after_ms(n, now, self.scale))
        return round(hint, 3)

    def _estimate_locked(self, backlog: int) -> float:
        rate = self._service_rate
        if not rate or backlog <= 0:
            return 0.0
        return backlog / rate * 1000.0

    def _shed_locked(self, tenant: str, n: int, reason: str,
                     retry: float) -> Admission:
        self.shed_total += n
        st = self._tenant_stats.setdefault(
            tenant, {"admitted": 0, "shed": 0})
        st["shed"] += n
        self.registry.inc("admission_shed_total", n)
        self.registry.inc(f"admission_shed_{reason}_total", n)
        return Admission(0, retry, reason)

    # ------------------------------------------------------------- reporting

    def snapshot(self) -> dict:
        """Controller state for reports: totals, knobs, per-tenant
        admitted/shed splits."""
        with self._lock:
            return {
                "admitted_total": self.admitted_total,
                "shed_total": self.shed_total,
                "scale": self.scale,
                "shed_probability": self.shed_probability,
                "service_rate_ops_s": self._service_rate,
                "tenants": {t: dict(st)
                            for t, st in self._tenant_stats.items()},
            }


class ControlPolicy:
    """AIMD closed loop: SLO burn → brake, recovery → release.

    Each :meth:`tick` reads the :class:`~fluidframework_tpu.utils.slo.
    SLOEngine` scorecard (side-effect-free — the policy reacting to a
    burn must not itself fire breach dumps). While ANY judged objective
    is burning on both its fast and slow windows, the budget scale is
    cut multiplicatively and the shed probability stepped up; on a
    healthy tick both recover additively toward wide open. The standard
    AIMD shape: convergence to fairness, fast reaction, gentle probe
    back.
    """

    def __init__(self, admission: AdmissionController, engine,
                 decrease: float = 0.5, increase: float = 0.1,
                 shed_step: float = 0.2, max_shed: float = 0.9,
                 min_scale: float = 0.05):
        self.admission = admission
        self.engine = engine
        self.decrease = decrease
        self.increase = increase
        self.shed_step = shed_step
        self.max_shed = max_shed
        self.min_scale = min_scale
        self.scale = 1.0
        self.shed_probability = 0.0
        self.ticks = 0
        self.breach_ticks = 0
        self.min_scale_seen = 1.0
        self.max_shed_seen = 0.0

    def tick(self, now: Optional[float] = None) -> dict:
        """One control step; call after the store's ``tick()`` sampled
        fresh metrics. Returns what moved (for sim traces)."""
        rows = self.engine.scorecard(now)
        burning = sorted({r["slo"] for r in rows
                          if r.get("judged") and not r["ok"]})
        self.ticks += 1
        if burning:
            self.breach_ticks += 1
            self.scale = max(self.min_scale, self.scale * self.decrease)
            self.shed_probability = min(
                self.max_shed, self.shed_probability + self.shed_step)
            REGISTRY.inc("admission_policy_brake_total")
        else:
            self.scale = min(1.0, self.scale + self.increase)
            self.shed_probability = max(
                0.0, self.shed_probability - self.shed_step)
        self.min_scale_seen = min(self.min_scale_seen, self.scale)
        self.max_shed_seen = max(self.max_shed_seen,
                                 self.shed_probability)
        self.admission.set_pressure(self.scale, self.shed_probability)
        return {"burning": burning, "scale": round(self.scale, 4),
                "shed_probability": round(self.shed_probability, 4)}
