"""Flight recorder: a bounded ring of recent telemetry, dumped on crash.

Reference counterpart: the black-box/flight-recorder pattern behind
production incident tooling (the reference service keeps recent structured
logs hot so an Alfred/Deli crash ships context, not just a stack trace).
Here: every telemetry event (``utils.telemetry`` routes ``send`` through
:func:`record`), tracer span, and faultpoint hit lands in a fixed-size
ring; when a faultpoint fires (``utils.faultpoints``) or a chaos drill
assertion fails (``testing.chaos``), the ring is dumped to JSONL so the
post-mortem has the last N events that led to the failure — structured
evidence instead of assertion text (ISSUE 2 / PR 1 follow-up).

The recorder is process-wide and always on: recording is one bounded
``deque.append`` per event, dumping happens only on failure. Dump files
rotate within a small window (``max_dumps``) so repeated drill crashes in
a test run cannot fill the disk.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional


#: name -> zero-arg provider whose output is embedded in every dump
#: header (``utils.capacity`` installs the census + metrics snapshot
#: so SLO-breach dumps carry the memory picture for offline forensics)
_DUMP_CONTEXT: Dict[str, Any] = {}


def add_dump_context(name: str, provider) -> None:
    """Register a provider whose return value lands in dump headers
    under ``name``. Providers must be cheap and must not raise (a
    raising provider is recorded as its repr, never propagated)."""
    _DUMP_CONTEXT[name] = provider


def remove_dump_context(name: str) -> None:
    _DUMP_CONTEXT.pop(name, None)


def _jsonable(value: Any) -> Any:
    """Best-effort JSON coercion for dump lines (events may carry file
    handles, numpy scalars, exceptions — the dump must never fail)."""
    try:
        json.dumps(value)
        return value
    except (TypeError, ValueError):
        return repr(value)


class FlightRecorder:
    """Bounded ring buffer of telemetry events + JSONL crash dumps."""

    def __init__(self, capacity: int = 4096, dump_dir: Optional[str] = None,
                 max_dumps: int = 64, dedup_window_s: float = 30.0):
        self.capacity = capacity
        self.enabled = True
        self.max_dumps = max_dumps
        #: per-reason rate limit: a reason that already dumped within this
        #: window is suppressed (counted, not written) — a chaos drill
        #: firing the same faultpoint N times writes ONE dump + a counter
        #: instead of spraying N near-identical files
        self.dedup_window_s = dedup_window_s
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._dump_seq = 0
        self._dump_dir = dump_dir
        #: paths written by :meth:`dump`, newest last (tests/operators
        #: read ``dumps[-1]`` to find the evidence file)
        self.dumps: List[str] = []
        #: reason -> count of dumps suppressed by the rate limit
        self.suppressed: Dict[str, int] = {}
        # (reason, dump_dir) -> (monotonic time, path) of the last real
        # dump; keyed on the dir too so a redirected FLUID_FLIGHT_DIR
        # (tests, per-incident dirs) always gets its first dump
        self._last_dump: Dict[tuple, tuple] = {}

    # ------------------------------------------------------------ recording

    def record(self, event: Dict[str, Any]) -> None:
        """Append one event dict to the ring (cheap; no copy of values)."""
        if self.enabled:
            self._ring.append({"ts": time.time(), **event})

    def note(self, name: str, **props: Any) -> None:
        """Record an ad-hoc named event (non-telemetry callers: faultpoint
        hits, drill failures, watchdog stalls)."""
        self.record({"eventName": name, **props})

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # -------------------------------------------------------------- dumping

    @property
    def dump_dir(self) -> str:
        return (self._dump_dir or os.environ.get("FLUID_FLIGHT_DIR")
                or tempfile.gettempdir())

    def dump(self, reason: str, path: Optional[str] = None,
             extra: Optional[dict] = None, force: bool = False) -> str:
        """Write the ring to JSONL: one header line (reason, wall time,
        event count), then one line per event, oldest first. Returns the
        path. Default paths rotate modulo ``max_dumps`` per process.

        Rate-limited per reason: a repeat of the same ``reason`` (into the
        same dump dir) within ``dedup_window_s`` is NOT written — the
        suppression is counted (``suppressed``, plus the process-wide
        ``flight_dump_suppressed_total`` counter) and the FIRST dump's
        path is returned, so callers still get evidence to point at.
        ``force=True`` bypasses the limit (operator-initiated dumps)."""
        dedup_key = (reason, self.dump_dir)
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(dedup_key)
            if not force and last is not None \
                    and now - last[0] < self.dedup_window_s:
                self.suppressed[reason] = self.suppressed.get(reason, 0) + 1
                n = self.suppressed[reason]
                self._ring.append({"ts": time.time(),
                                   "eventName": "flight_dump_suppressed",
                                   "reason": reason, "suppressed": n})
                _count_dump(suppressed=True)
                return last[1]
            events = list(self._ring)
            if path is None:
                name = (f"flight-{os.getpid()}-"
                        f"{self._dump_seq % self.max_dumps}.jsonl")
                path = os.path.join(self.dump_dir, name)
            self._dump_seq += 1
        header = {"flight_recorder": reason, "dumped_at": time.time(),
                  "n_events": len(events), **(extra or {})}
        # dump-time context (capacity census, metrics snapshot): best
        # effort — forensics context must never block the evidence write
        for ctx_name, provider in list(_DUMP_CONTEXT.items()):
            try:
                header.setdefault(ctx_name, provider())
            except Exception as e:
                header.setdefault(ctx_name, repr(e))
        with open(path, "w") as f:
            f.write(json.dumps(
                {k: _jsonable(v) for k, v in header.items()}) + "\n")
            for e in events:
                f.write(json.dumps(
                    {k: _jsonable(v) for k, v in e.items()}) + "\n")
            f.flush()
            os.fsync(f.fileno())
        with self._lock:
            self.dumps.append(path)
            del self.dumps[:-self.max_dumps]
            # recorded only after the write landed: a failed write must
            # not arm the rate limit and suppress the retry's evidence
            self._last_dump[dedup_key] = (now, path)
        _count_dump(suppressed=False)
        return path


def _count_dump(suppressed: bool) -> None:
    """Count dumps/suppressions on the process metrics registry (late
    import: telemetry imports this module at load time). The counter is
    what the ``flight_dump_rate == 0`` SLO watches — a healthy steady
    state writes zero dumps."""
    from .telemetry import REGISTRY
    REGISTRY.inc("flight_dump_suppressed_total" if suppressed
                 else "flight_dump_total")


#: the process-wide recorder (telemetry/faultpoints/chaos all feed it)
RECORDER = FlightRecorder()


def record(event: Dict[str, Any]) -> None:
    RECORDER.record(event)


def note(name: str, **props: Any) -> None:
    RECORDER.note(name, **props)


def dump(reason: str, path: Optional[str] = None,
         extra: Optional[dict] = None) -> str:
    return RECORDER.dump(reason, path, extra)


def load_dump(path: str) -> List[dict]:
    """Read a dump back: list of dicts, header first (trace_viewer and
    tests use this; tolerant of a torn tail the same way oplog recovery
    is — a crash mid-dump keeps the complete prefix)."""
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                break
    return out
