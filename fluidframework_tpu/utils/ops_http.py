"""Tiny threaded HTTP server for the live operations plane (ISSUE 17).

Stdlib-only (``http.server`` + ``socketserver``): no framework, no new
dependencies, no event loop — each request is handled on its own daemon
thread so a scrape can never block (or be blocked by) the asyncio
ingress loops or the ingest executor workers.

The server is a dumb router: callers register ``path -> handler`` where
a handler takes the parsed query dict and returns ``(content_type,
body_bytes)``.  Everything about *what* is served (Prometheus
exposition, SLO scorecards, flight rings, span trees, hot-doc sketches)
lives in :mod:`fluidframework_tpu.server.opsd`; this module only owns
sockets and threads so it can be reused by tools and tests.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

__all__ = ["OpsHTTPServer", "json_body"]

#: a route handler: (query dict) -> (content-type, body bytes)
Handler = Callable[[Dict[str, str]], Tuple[str, bytes]]


def json_body(obj) -> Tuple[str, bytes]:
    """Serialize ``obj`` for an HTTP response, mapping non-finite floats
    to ``null`` so the output stays strict RFC 8259 JSON (SLO scorecards
    carry ``inf`` burn rates when a window has no samples)."""
    text = json.dumps(obj, default=_jsonable, allow_nan=False)
    return ("application/json; charset=utf-8", text.encode("utf-8"))


def _jsonable(v):
    try:
        return str(v)
    except Exception:
        return None


class _Handler(BaseHTTPRequestHandler):
    # per-request threads must not linger when a scraper goes away
    timeout = 10
    protocol_version = "HTTP/1.1"
    server_version = "fluid-opsd"

    def do_GET(self) -> None:  # noqa: N802 (stdlib naming)
        parsed = urlparse(self.path)
        route = self.server.routes.get(parsed.path)  # type: ignore[attr-defined]
        if route is None:
            body = json.dumps(
                {"error": "no such route",
                 "routes": sorted(self.server.routes)}).encode()
            self._reply(404, "application/json; charset=utf-8", body)
            return
        query = {k: v[-1] for k, v in parse_qs(parsed.query).items()}
        try:
            ctype, body = route(query)
        except Exception as exc:  # surface handler bugs to the scraper
            body = json.dumps({"error": repr(exc)}).encode()
            self._reply(500, "application/json; charset=utf-8", body)
            return
        self._reply(200, ctype, body)

    def _reply(self, code: int, ctype: str, body: bytes) -> None:
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper hung up mid-response; nothing to do

    def log_message(self, fmt: str, *args) -> None:
        pass  # stay silent: scrapes at 1 Hz would spam stderr


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    # restart-after-crash friendliness (chaos_soak crash_restart re-binds)
    allow_reuse_address = True

    def __init__(self, addr, routes: Dict[str, Handler]):
        self.routes = routes
        super().__init__(addr, _Handler)


class OpsHTTPServer:
    """Threaded HTTP server with explicit route registration.

    ``port=0`` binds an ephemeral port; read ``.port`` after
    :meth:`start`.  ``start``/``stop`` are idempotent and the instance
    doubles as a context manager.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self._want_port = port
        self.port: int = port
        self._routes: Dict[str, Handler] = {}
        self._httpd: Optional[_Server] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- routes

    def route(self, path: str, handler: Handler) -> "OpsHTTPServer":
        """Register ``handler`` for exact-match ``path``. Chainable."""
        self._routes[path] = handler
        return self

    @property
    def routes(self) -> Dict[str, Handler]:
        return dict(self._routes)

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "OpsHTTPServer":
        if self._httpd is not None:
            return self
        self._httpd = _Server((self.host, self._want_port), self._routes)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name=f"opsd-http:{self.port}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        httpd, thread = self._httpd, self._thread
        self._httpd = self._thread = None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def __enter__(self) -> "OpsHTTPServer":
        return self.start()

    def __exit__(self, *_exc) -> None:
        self.stop()
