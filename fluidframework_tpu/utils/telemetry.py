"""Structured telemetry: loggers, performance spans, sampled counters.

Reference counterpart: ``@fluidframework/telemetry-utils`` —
``ITelemetryLogger``/``createChildLogger``, ``PerformanceEvent.timedExec``,
``LoggingError`` tagging, ``sampledTelemetry`` — SURVEY.md §2.15, §5.1
(mount empty). Host-pluggable sink (the reference delivers events to a
host-provided ``ITelemetryBaseLogger``); span taxonomy mirrors the
reference's hot paths: ``load`` / ``catchup`` / ``opApply`` / ``summarize``.

TPU-first addition (§5.5): ``MetricsCollector`` — per-step counters and
latency histograms (ops merged, docs touched, p50/p99 apply latency)
exported from the host loop, the role Prometheus metrics play server-side
in the reference.
"""

from __future__ import annotations

import bisect
import time
from typing import Any, Callable, Dict, List, Optional

# event categories (reference: ITelemetryBaseEvent.category)
GENERIC = "generic"
PERFORMANCE = "performance"
ERROR = "error"
WARNING = "warning"   # degraded-but-serving conditions (shed load, stalls)

Sink = Callable[[dict], None]


class TelemetryLogger:
    """Namespaced structured logger (reference: ITelemetryLoggerExt).

    Events are flat dicts: ``{category, eventName, ...props}``; namespaces
    chain with ``:`` like the reference's logger namespaces.
    """

    def __init__(self, sink: Optional[Sink] = None, namespace: str = "",
                 props: Optional[Dict[str, Any]] = None):
        self._sink = sink
        self.namespace = namespace
        self.props = dict(props or {})

    def child(self, namespace: str,
              props: Optional[Dict[str, Any]] = None) -> "TelemetryLogger":
        """Reference: createChildLogger — inherits sink + props."""
        ns = f"{self.namespace}:{namespace}" if self.namespace else namespace
        return TelemetryLogger(self._sink, ns, {**self.props, **(props or {})})

    def send(self, category: str, event_name: str, **props) -> None:
        if self._sink is None:
            return
        name = f"{self.namespace}:{event_name}" if self.namespace \
            else event_name
        self._sink({"category": category, "eventName": name,
                    **self.props, **props})

    def send_event(self, event_name: str, **props) -> None:
        self.send(GENERIC, event_name, **props)

    def send_error(self, event_name: str, error: Optional[Exception] = None,
                   **props) -> None:
        if error is not None:
            props.setdefault("error", repr(error))
            props.setdefault("errorType", type(error).__name__)
        self.send(ERROR, event_name, **props)

    def send_warning(self, event_name: str, **props) -> None:
        """Degradation events: the system is still serving but shedding
        load or running slow — these must be VISIBLE (replica overflow,
        slow-consumer evictions, apply stalls), never silent."""
        self.send(WARNING, event_name, **props)

    def performance_event(self, event_name: str,
                          **props) -> "PerformanceEvent":
        return PerformanceEvent(self, event_name, props)


class PerformanceEvent:
    """Timed span (reference: PerformanceEvent.timedExec): emits ``_start``
    on enter and ``_end`` (with duration_ms) or ``_cancel`` (with the error)
    on exit. Use as a context manager."""

    def __init__(self, logger: TelemetryLogger, event_name: str,
                 props: Dict[str, Any],
                 clock: Callable[[], float] = time.perf_counter):
        self.logger = logger
        self.event_name = event_name
        self.props = props
        self.clock = clock
        self._t0: Optional[float] = None
        self.duration_ms: Optional[float] = None

    def __enter__(self) -> "PerformanceEvent":
        self._t0 = self.clock()
        self.logger.send(PERFORMANCE, f"{self.event_name}_start",
                         **self.props)
        return self

    def __exit__(self, exc_type, exc, _tb) -> None:
        self.duration_ms = (self.clock() - self._t0) * 1e3
        if exc is None:
            self.logger.send(PERFORMANCE, f"{self.event_name}_end",
                             duration_ms=self.duration_ms, **self.props)
        else:
            self.logger.send(ERROR, f"{self.event_name}_cancel",
                             duration_ms=self.duration_ms, error=repr(exc),
                             **self.props)


class SampledTelemetry:
    """Emit one aggregated event every ``rate`` records (reference:
    sampledTelemetry for hot-loop counters)."""

    def __init__(self, logger: TelemetryLogger, event_name: str,
                 rate: int = 1000):
        self.logger = logger
        self.event_name = event_name
        self.rate = rate
        self.count = 0
        self.total = 0.0

    def record(self, value: float = 1.0) -> None:
        self.count += 1
        self.total += value
        if self.count >= self.rate:
            self.flush()

    def flush(self) -> None:
        if self.count:
            self.logger.send(PERFORMANCE, self.event_name,
                             samples=self.count, total=self.total,
                             mean=self.total / self.count)
            self.count = 0
            self.total = 0.0


class Histogram:
    """Fixed-bucket latency histogram with percentile reads."""

    def __init__(self, buckets_ms: Optional[List[float]] = None):
        # log-spaced defaults covering 10 µs .. 10 s
        self.bounds = buckets_ms if buckets_ms is not None else [
            0.01 * (10 ** (i / 4)) for i in range(25)]
        self.counts = [0] * (len(self.bounds) + 1)
        self.n = 0

    def record(self, value_ms: float) -> None:
        self.counts[bisect.bisect_left(self.bounds, value_ms)] += 1
        self.n += 1

    def percentile(self, p: float) -> float:
        """Upper bound of the bucket containing the p-th percentile."""
        if self.n == 0:
            return 0.0
        target = p / 100.0 * self.n
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return self.bounds[i] if i < len(self.bounds) \
                    else float("inf")
        return float("inf")


class MetricsCollector:
    """Host-loop counters + latency histograms (SURVEY.md §5.5): the
    client-side analog of the reference server's per-lambda Prometheus
    metrics (op rate, lag, pending ops)."""

    def __init__(self):
        self.counters: Dict[str, float] = {}
        self.histograms: Dict[str, Histogram] = {}

    def inc(self, name: str, by: float = 1.0) -> None:
        self.counters[name] = self.counters.get(name, 0.0) + by

    def observe(self, name: str, value_ms: float) -> None:
        if name not in self.histograms:
            self.histograms[name] = Histogram()
        self.histograms[name].record(value_ms)

    def snapshot(self) -> dict:
        out: Dict[str, Any] = dict(self.counters)
        for name, h in self.histograms.items():
            out[f"{name}_p50_ms"] = h.percentile(50)
            out[f"{name}_p99_ms"] = h.percentile(99)
            out[f"{name}_count"] = h.n
        return out


def console_sink(event: dict) -> None:
    """Debug sink: one line per event."""
    print(" ".join(f"{k}={v}" for k, v in event.items()))


class BufferSink:
    """Test/inspection sink: collects events in memory."""

    def __init__(self):
        self.events: List[dict] = []

    def __call__(self, event: dict) -> None:
        self.events.append(event)

    def named(self, suffix: str) -> List[dict]:
        return [e for e in self.events
                if e["eventName"].endswith(suffix)]
