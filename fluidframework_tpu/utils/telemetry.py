"""Structured telemetry: loggers, performance spans, sampled counters.

Reference counterpart: ``@fluidframework/telemetry-utils`` —
``ITelemetryLogger``/``createChildLogger``, ``PerformanceEvent.timedExec``,
``LoggingError`` tagging, ``sampledTelemetry`` — SURVEY.md §2.15, §5.1
(mount empty). Host-pluggable sink (the reference delivers events to a
host-provided ``ITelemetryBaseLogger``); span taxonomy mirrors the
reference's hot paths: ``load`` / ``catchup`` / ``opApply`` / ``summarize``.

TPU-first addition (§5.5): ``MetricsRegistry`` — a process-wide registry of
counters, gauges, and latency histograms with Prometheus-style text
exposition, the role Prometheus metrics play server-side in the reference.
``MetricsCollector`` (the historical per-engine name) is the same class;
per-component collectors ``attach`` to the global :data:`REGISTRY` so one
``snapshot()``/``render_prometheus()`` covers the whole process (ISSUE 2).

Every event sent through a :class:`TelemetryLogger` — sink or no sink —
is also recorded into the process flight recorder
(``utils.flight_recorder``), so a crash dump carries the recent telemetry
stream of every layer.
"""

from __future__ import annotations

import bisect
import time
import weakref
from typing import Any, Callable, Dict, List, Optional

from . import flight_recorder as _flight

# event categories (reference: ITelemetryBaseEvent.category)
GENERIC = "generic"
PERFORMANCE = "performance"
ERROR = "error"
WARNING = "warning"   # degraded-but-serving conditions (shed load, stalls)

Sink = Callable[[dict], None]


class TelemetryLogger:
    """Namespaced structured logger (reference: ITelemetryLoggerExt).

    Events are flat dicts: ``{category, eventName, ...props}``; namespaces
    chain with ``:`` like the reference's logger namespaces.
    """

    def __init__(self, sink: Optional[Sink] = None, namespace: str = "",
                 props: Optional[Dict[str, Any]] = None):
        self._sink = sink
        self.namespace = namespace
        self.props = dict(props or {})

    def child(self, namespace: str,
              props: Optional[Dict[str, Any]] = None) -> "TelemetryLogger":
        """Reference: createChildLogger — inherits sink + props."""
        ns = f"{self.namespace}:{namespace}" if self.namespace else namespace
        return TelemetryLogger(self._sink, ns, {**self.props, **(props or {})})

    def send(self, category: str, event_name: str, **props) -> None:
        name = f"{self.namespace}:{event_name}" if self.namespace \
            else event_name
        event = {"category": category, "eventName": name,
                 **self.props, **props}
        # every event — sinked or not — feeds the crash flight recorder
        _flight.record(event)
        if self._sink is not None:
            self._sink(event)

    def send_event(self, event_name: str, **props) -> None:
        self.send(GENERIC, event_name, **props)

    def send_error(self, event_name: str, error: Optional[Exception] = None,
                   **props) -> None:
        if error is not None:
            props.setdefault("error", repr(error))
            props.setdefault("errorType", type(error).__name__)
        self.send(ERROR, event_name, **props)

    def send_warning(self, event_name: str, **props) -> None:
        """Degradation events: the system is still serving but shedding
        load or running slow — these must be VISIBLE (replica overflow,
        slow-consumer evictions, apply stalls), never silent."""
        self.send(WARNING, event_name, **props)

    def performance_event(self, event_name: str,
                          **props) -> "PerformanceEvent":
        return PerformanceEvent(self, event_name, props)


class PerformanceEvent:
    """Timed span (reference: PerformanceEvent.timedExec): emits ``_start``
    on enter and ``_end`` (with duration_ms) or ``_cancel`` (with the error)
    on exit. Use as a context manager."""

    def __init__(self, logger: TelemetryLogger, event_name: str,
                 props: Dict[str, Any],
                 clock: Callable[[], float] = time.perf_counter):
        self.logger = logger
        self.event_name = event_name
        self.props = props
        self.clock = clock
        self._t0: Optional[float] = None
        self.duration_ms: Optional[float] = None

    def __enter__(self) -> "PerformanceEvent":
        self._t0 = self.clock()
        self.logger.send(PERFORMANCE, f"{self.event_name}_start",
                         **self.props)
        return self

    def __exit__(self, exc_type, exc, _tb) -> None:
        self.duration_ms = (self.clock() - self._t0) * 1e3
        if exc is None:
            self.logger.send(PERFORMANCE, f"{self.event_name}_end",
                             duration_ms=self.duration_ms, **self.props)
        else:
            self.logger.send(ERROR, f"{self.event_name}_cancel",
                             duration_ms=self.duration_ms, error=repr(exc),
                             **self.props)


class SampledTelemetry:
    """Emit one aggregated event every ``rate`` records (reference:
    sampledTelemetry for hot-loop counters)."""

    def __init__(self, logger: TelemetryLogger, event_name: str,
                 rate: int = 1000):
        self.logger = logger
        self.event_name = event_name
        self.rate = rate
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def record(self, value: float = 1.0) -> None:
        self.count += 1
        self.total += value
        # track extremes so outliers (a 983 ms stall in a 1000-sample
        # window) survive aggregation instead of vanishing into the mean
        if self.min is None or value < self.min:
            self.min = value
        if self.max is None or value > self.max:
            self.max = value
        if self.count >= self.rate:
            self.flush()

    def flush(self) -> None:
        if self.count:
            self.logger.send(PERFORMANCE, self.event_name,
                             samples=self.count, total=self.total,
                             mean=self.total / self.count,
                             min=self.min, max=self.max)
            self.count = 0
            self.total = 0.0
            self.min = None
            self.max = None

    def close(self) -> None:
        """Flush any partial window (call on shutdown — a tail of
        ``count < rate`` records would otherwise be lost)."""
        self.flush()

    def __enter__(self) -> "SampledTelemetry":
        return self

    def __exit__(self, *_exc) -> None:
        self.close()


class Histogram:
    """Fixed-bucket latency histogram with percentile reads.

    ``observe(value, exemplar=...)`` additionally captures *exemplars* —
    (value, trace context) pairs in the Prometheus-exemplar sense — so an
    SLO breach on a percentile can name the trace id of the worst sample
    instead of just a number (utils.slo tags its flight dumps with it).
    """

    #: recent exemplars retained per histogram (bounded: hot paths observe
    #: millions of samples; only the newest few are diagnostic)
    EXEMPLAR_KEEP = 16

    def __init__(self, buckets_ms: Optional[List[float]] = None):
        # log-spaced defaults covering 10 µs .. 10 s
        self.bounds = buckets_ms if buckets_ms is not None else [
            0.01 * (10 ** (i / 4)) for i in range(25)]
        self.counts = [0] * (len(self.bounds) + 1)
        self.n = 0
        #: running sum of observed values — the Prometheus ``_sum`` sample;
        #: also what latency attribution needs for exact (not
        #: bucket-quantized) per-stage means
        self.sum_ms = 0.0
        #: newest-last (value_ms, trace_id, span_id) triples
        self.exemplars: List[tuple] = []
        #: the exemplar with the largest value ever observed — the sample
        #: an SLO post-mortem wants (the worst, not the latest)
        self.worst_exemplar: Optional[tuple] = None

    def record(self, value_ms: float) -> None:
        self.counts[bisect.bisect_left(self.bounds, value_ms)] += 1
        self.n += 1
        self.sum_ms += value_ms

    @property
    def mean(self) -> float:
        """Exact mean of observed values (0.0 when empty)."""
        return self.sum_ms / self.n if self.n else 0.0

    def observe(self, value_ms: float, exemplar: Any = None) -> None:
        """Record a sample; ``exemplar`` may be a ``TraceContext``-like
        object (``trace_id``/``span_id`` attrs), or ``True`` to capture
        the thread's current trace context (no-op when none is active).
        ``None`` (the default) records with zero exemplar overhead."""
        self.record(value_ms)
        if exemplar is None:
            return
        if exemplar is True:
            from . import tracing  # late: tracing imports telemetry
            exemplar = tracing.current()
            if exemplar is None:
                return
        entry = (value_ms, getattr(exemplar, "trace_id", None),
                 getattr(exemplar, "span_id", None))
        self.exemplars.append(entry)
        del self.exemplars[:-self.EXEMPLAR_KEEP]
        if self.worst_exemplar is None or value_ms >= self.worst_exemplar[0]:
            self.worst_exemplar = entry

    def percentile(self, p: float) -> float:
        """Upper bound of the bucket containing the p-th percentile.
        Returns ``inf`` when the percentile lands in the open-ended
        overflow bucket — check :attr:`overflow` to see how many values
        exceeded the last bound."""
        if self.n == 0:
            return 0.0
        target = p / 100.0 * self.n
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return self.bounds[i] if i < len(self.bounds) \
                    else float("inf")
        return float("inf")

    @property
    def overflow(self) -> int:
        """Count of recorded values past the last bucket bound (the
        values ``percentile`` reports as ``inf``)."""
        return self.counts[-1]


#: fine log-spaced buckets (16 per decade vs the default 4) for the
#: per-stage ingest timings: with quarter-decade buckets a p50 read
#: quantizes a real 25 ms to the 31.6 ms bound — too coarse to check a
#: ≤30 ms budget against. 0.1 ms .. ~5.6 s.
_FINE_BOUNDS = [0.1 * (10 ** (i / 16)) for i in range(75)]

#: the stage-attribution grid keeps the fine sub-ms resolution but
#: extends to ~100 s: under a contended storm the rx→ack end-to-end
#: timeline legitimately reaches tens of seconds (windows queue behind
#: the executor), and a p99 that falls off the grid reads as ``inf`` —
#: useless as the sharding signal the breakdown exists to provide
_STAGE_BOUNDS = [0.1 * (10 ** (i / 16)) for i in range(97)]

#: name-prefix → bucket preset applied when ``observe`` lazily creates a
#: histogram; first matching prefix wins
BUCKET_PRESETS: List[tuple] = [
    ("ingest_", _FINE_BOUNDS),
    # latency-attribution stage segments (ISSUE 17): sub-ms segments like
    # the admission fence need the fine grid too
    ("stage_", _STAGE_BOUNDS),
]


def _buckets_for(name: str) -> Optional[List[float]]:
    for prefix, bounds in BUCKET_PRESETS:
        if name.startswith(prefix):
            return list(bounds)
    return None


class MetricsRegistry:
    """Process-wide counters, gauges, and latency histograms (SURVEY.md
    §5.5): the analog of the reference server's per-lambda Prometheus
    metrics (op rate, lag, pending ops), with Prometheus-style text
    exposition. Component-local instances (one per serving engine)
    ``attach`` to the module's global :data:`REGISTRY` so one snapshot
    covers the whole process."""

    def __init__(self):
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.histograms: Dict[str, Histogram] = {}
        # key -> weakref to an attached component registry: engines come
        # and go (tests build hundreds); the global registry must not
        # keep them alive
        self._components: Dict[str, Any] = {}
        # key -> label dict for label-qualified attachments (shard=,
        # replica=, partition= — the mesh rollup scheme, ISSUE 4)
        self._component_labels: Dict[str, Dict[str, str]] = {}

    # ----------------------------------------------------------- recording

    def inc(self, name: str, by: float = 1.0) -> None:
        self.counters[name] = self.counters.get(name, 0.0) + by

    def set_gauge(self, name: str, value: float) -> None:
        self.gauges[name] = value

    def observe(self, name: str, value_ms: float,
                exemplar: Any = None) -> None:
        if name not in self.histograms:
            self.histograms[name] = Histogram(_buckets_for(name))
        self.histograms[name].observe(value_ms, exemplar=exemplar)

    # ---------------------------------------------------------- components

    @staticmethod
    def component_key(name: str, labels: Optional[Dict[str, Any]]) -> str:
        """The snapshot key for an attachment: ``name`` bare, or
        ``name{k=v,...}`` with sorted label keys — two engines of the
        same family with different labels can never shadow each other."""
        if not labels:
            return name
        inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
        return f"{name}{{{inner}}}"

    def attach(self, name: str, registry: "MetricsRegistry",
               labels: Optional[Dict[str, Any]] = None) -> str:
        """Register a component-local registry for global exposition.

        ``labels`` qualify the key (``name{shard=0}``): the mesh rollup
        scheme — per-shard / per-replica / per-partition collectors stay
        distinct series in ``full_snapshot()`` and the Prometheus text.
        Unlabeled (or same-label) collisions between *different* live
        registries auto-suffix the name (several engines of the same
        family in one process). Returns the key used."""
        base, i = name, 1
        while True:
            key = self.component_key(name, labels)
            ref = self._components.get(key)
            if ref is None or ref() is None or ref() is registry:
                break
            i += 1
            name = f"{base}{i}"
        self._components[key] = weakref.ref(registry)
        if labels:
            self._component_labels[key] = {
                k: str(v) for k, v in labels.items()}
        return key

    def components(self) -> Dict[str, "MetricsRegistry"]:
        live = {}
        for key, ref in list(self._components.items()):
            reg = ref()
            if reg is None:
                del self._components[key]
                self._component_labels.pop(key, None)
            else:
                live[key] = reg
        return live

    def component_labels(self, key: str) -> Dict[str, str]:
        """Labels a component was attached with (empty for bare names)."""
        return dict(self._component_labels.get(key, {}))

    # ------------------------------------------------------------ snapshot

    def snapshot(self) -> dict:
        """Flat dict: counters verbatim, gauges verbatim, and per-
        histogram ``_p50_ms``/``_p99_ms``/``_count``/``_overflow``."""
        out: Dict[str, Any] = dict(self.counters)
        out.update(self.gauges)
        for name, h in self.histograms.items():
            out[f"{name}_p50_ms"] = h.percentile(50)
            out[f"{name}_p99_ms"] = h.percentile(99)
            out[f"{name}_count"] = h.n
            out[f"{name}_overflow"] = h.overflow
        return out

    def full_snapshot(self) -> dict:
        """Own snapshot + every live attached component's, prefixed
        ``{component}.{metric}`` — the process-wide metric set bench.py
        embeds in BENCH json. Sharded attachments (components labeled
        ``shard=``) additionally roll up into computed cross-shard skew
        keys: ``{name}.ops_applied_shard_{min,max,skew}`` — the max/min
        ops-applied imbalance is the load-balance health signal."""
        out = self.snapshot()
        shard_groups: Dict[str, List[float]] = {}
        for key, reg in self.components().items():
            for k, v in reg.snapshot().items():
                out[f"{key}.{k}"] = v
            labels = self._component_labels.get(key)
            if labels and "shard" in labels:
                base = key.split("{", 1)[0]
                shard_groups.setdefault(base, []).append(
                    float(reg.counters.get("ops_applied", 0.0)))
        for base, counts in shard_groups.items():
            if len(counts) >= 2:
                out[f"{base}.ops_applied_shard_min"] = min(counts)
                out[f"{base}.ops_applied_shard_max"] = max(counts)
                out[f"{base}.ops_applied_shard_skew"] = \
                    max(counts) - min(counts)
        return out

    def snapshot_kinds(self) -> Dict[str, str]:
        """Kind of every key ``snapshot()`` emits: ``counter`` | ``gauge``
        | ``quantile`` (histogram percentile reads — point-in-time, never
        rate-derived). Histogram ``_count``/``_overflow`` keys are
        cumulative and classified ``counter``. The time-series layer
        (utils.timeseries) uses this to decide which series get
        counter→rate derivation."""
        kinds: Dict[str, str] = {}
        for k in self.counters:
            kinds[k] = "counter"
        for k in self.gauges:
            kinds[k] = "gauge"
        for name in self.histograms:
            kinds[f"{name}_p50_ms"] = "quantile"
            kinds[f"{name}_p99_ms"] = "quantile"
            kinds[f"{name}_count"] = "counter"
            kinds[f"{name}_overflow"] = "counter"
        return kinds

    def full_snapshot_kinds(self) -> Dict[str, str]:
        """``snapshot_kinds`` over the full (component-prefixed) key set;
        computed skew keys are gauges."""
        kinds = self.snapshot_kinds()
        for key, reg in self.components().items():
            for k, kind in reg.snapshot_kinds().items():
                kinds[f"{key}.{k}"] = kind
            labels = self._component_labels.get(key)
            if labels and "shard" in labels:
                base = key.split("{", 1)[0]
                for suffix in ("min", "max", "skew"):
                    kinds[f"{base}.ops_applied_shard_{suffix}"] = "gauge"
        return kinds

    def find_histogram(self, snapshot_key: str) -> Optional[Histogram]:
        """The Histogram behind a full-snapshot key (e.g.
        ``StringServingEngine.flush_ms_p99_ms`` → that engine's
        ``flush_ms`` histogram), or None — the SLO engine resolves breach
        exemplars through this."""
        comp, _, metric = snapshot_key.rpartition(".")
        reg = self if not comp else self.components().get(comp)
        if reg is None:
            return None
        for suffix in ("_p50_ms", "_p99_ms", "_count", "_overflow"):
            if metric.endswith(suffix):
                metric = metric[:-len(suffix)]
                break
        return reg.histograms.get(metric)

    def render_prometheus(self, include_components: bool = True) -> str:
        """Prometheus text exposition (counters/gauges as single samples,
        histograms as cumulative ``_bucket`` lines plus ``_sum``/``_count``
        — bounds are upper edges in ms, ``+Inf`` is the overflow bucket).
        Labeled attachments carry their labels on every sample
        (``component="StringServingEngine",shard="3"``) — the per-shard /
        per-replica / per-partition series of the mesh rollup scheme.
        Label values are escaped per the text-format spec (backslash,
        double quote, newline); serve with content-type
        :data:`PROM_CONTENT_TYPE`."""
        lines: List[str] = []

        def emit(prefix: str, reg: "MetricsRegistry",
                 labels: Optional[Dict[str, str]] = None) -> None:
            pairs = ([f'component="{_prom_label_value(prefix)}"']
                     if prefix else []) + \
                [f'{k}="{_prom_label_value(v)}"'
                 for k, v in sorted((labels or {}).items())]
            lab = "{" + ",".join(pairs) + "}" if pairs else ""
            comp = ",".join(pairs) + "," if pairs else ""
            for k in sorted(reg.counters):
                lines.append(f"# TYPE {_prom_name(k)} counter")
                lines.append(f"{_prom_name(k)}{lab} {reg.counters[k]}")
            for k in sorted(reg.gauges):
                lines.append(f"# TYPE {_prom_name(k)} gauge")
                lines.append(f"{_prom_name(k)}{lab} {reg.gauges[k]}")
            for k in sorted(reg.histograms):
                h = reg.histograms[k]
                name = _prom_name(k)
                lines.append(f"# TYPE {name} histogram")
                cum = 0
                for bound, c in zip(h.bounds, h.counts):
                    cum += c
                    lines.append(
                        f'{name}_bucket{{{comp}le="{bound:g}"}} {cum}')
                lines.append(f'{name}_bucket{{{comp}le="+Inf"}} {h.n}')
                lines.append(f"{name}_sum{lab} {h.sum_ms}")
                lines.append(f"{name}_count{lab} {h.n}")

        emit("", self)
        if include_components:
            for key, reg in sorted(self.components().items()):
                emit(key.split("{", 1)[0], reg,
                     self._component_labels.get(key))
        return "\n".join(lines) + "\n"


#: exposition content-type for :meth:`MetricsRegistry.render_prometheus`
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _prom_name(name: str) -> str:
    """Sanitize a metric name for Prometheus exposition."""
    return "".join(ch if ch.isalnum() or ch == "_" else "_"
                   for ch in name)


def _prom_label_value(value: Any) -> str:
    """Escape a label value per the Prometheus text format: backslash,
    double quote, and line feed are the three characters that would
    otherwise break a scraper's line/quote parse."""
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


class StageClock:
    """Per-stage busy-time accounting for a pipelined executor (the
    ingest pipeline's stage-occupancy/overlap instrument).

    Each worker thread adds its stage's busy wall after every unit of
    work; ``occupancy()`` divides per-stage busy time by the clock's open
    wall-span (how loaded each worker is), and ``overlap()`` is the sum
    of all stages' busy time over the span — a value above 1.0 is direct
    evidence that stages genuinely ran concurrently (a serial stage walk
    can never exceed 1.0)."""

    def __init__(self, stages):
        import threading
        self.stages = tuple(stages)
        self.busy_ms: Dict[str, float] = {s: 0.0 for s in self.stages}
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()

    def add(self, stage: str, ms: float) -> None:
        with self._lock:
            self.busy_ms[stage] += ms

    def span_ms(self) -> float:
        return (time.perf_counter() - self._t0) * 1000

    def occupancy(self) -> Dict[str, float]:
        span = self.span_ms() or 1.0
        with self._lock:
            return {s: self.busy_ms[s] / span for s in self.stages}

    def overlap(self) -> float:
        span = self.span_ms() or 1.0
        with self._lock:
            return sum(self.busy_ms.values()) / span


#: back-compat name — per-engine collectors ARE registries
MetricsCollector = MetricsRegistry

#: the process-wide registry: dark-layer instrumentation (oplog,
#: summarizer, container runtime, kernels, ingress) counts here, and
#: component registries attach for unified exposition
REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY


def console_sink(event: dict) -> None:
    """Debug sink: one line per event."""
    print(" ".join(f"{k}={v}" for k, v in event.items()))


class BufferSink:
    """Test/inspection sink: collects events in memory."""

    def __init__(self):
        self.events: List[dict] = []

    def __call__(self, event: dict) -> None:
        self.events.append(event)

    def named(self, suffix: str) -> List[dict]:
        return [e for e in self.events
                if e["eventName"].endswith(suffix)]
