"""Declarative SLOs with multi-window burn-rate evaluation.

Reference counterpart: the SRE-workbook alerting lineage the reference
service's lag/latency alerts follow — an objective is declared once
(``ack_p99_ms < 200``) and judged over TWO windows: a *fast* window that
catches a cliff within seconds and a *slow* window that keeps one bad
sample from paging. A breach requires both windows to be burning, the
standard multi-window multi-burn-rate shape: fast-only is noise, slow-only
is stale history.

Specs evaluate over a :class:`~fluidframework_tpu.utils.timeseries.\
TimeSeriesStore` (never raw snapshots — an SLO is a statement about a
window, not an instant). Breaches are edge-triggered: the first tick a
spec crosses into breach it (a) increments ``slo_breach_total``, (b)
emits a warning telemetry event, and (c) dumps the flight recorder
tagged with the breaching SLO and the worst sample's trace id — resolved
from the metric's histogram exemplars (``Histogram.observe(exemplar=)``)
when it has one, else the thread's current trace context. Subsequent
ticks in the same breach stay quiet until the spec recovers (re-arm).

``tools/healthz.py`` renders the scorecard; bench.py embeds it in the
BENCH record so ``tools/perf_sentinel.py`` and humans judge a round by
the same targets.
"""

from __future__ import annotations

import fnmatch
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import flight_recorder, telemetry
from .timeseries import TimeSeriesStore

#: comparison operators an SLO may declare, longest-first for parsing
_OPS = ("<=", ">=", "==", "!=", "<", ">")


def _compare(value: float, op: str, threshold: float) -> bool:
    """True when ``value`` satisfies the objective."""
    if op == "<":
        return value < threshold
    if op == "<=":
        return value <= threshold
    if op == ">":
        return value > threshold
    if op == ">=":
        return value >= threshold
    if op == "==":
        return value == threshold
    return value != threshold


@dataclass
class SLOSpec:
    """One declarative objective over a metric pattern.

    ``metric`` is an fnmatch pattern against time-series names (so
    ``*.ack_ms_p99_ms`` covers every engine's histogram); ``kind`` is
    ``value`` (judge each sample) or ``rate`` (judge the counter's
    derived per-second rate over each window — ``flight_dump_rate == 0``
    is ``rate`` over ``flight_dump_total``). Burn thresholds are the
    fraction of window samples allowed to violate before that window is
    "burning": fast defaults strict (half the window bad), slow defaults
    lenient (a tenth) per the workbook's fast/slow pairing.
    """

    name: str
    metric: str
    op: str
    threshold: float
    kind: str = "value"            # "value" | "rate"
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    fast_burn: float = 0.5
    slow_burn: float = 0.1
    #: samples required in the fast window before judging (a spec with
    #: one sample is opinion, not measurement)
    min_samples: int = 2

    @classmethod
    def parse(cls, text: str, name: Optional[str] = None,
              **overrides: Any) -> "SLOSpec":
        """Build a spec from ``"metric OP threshold"`` — the form the
        docs/ISSUE write SLOs in. ``true``/``false`` thresholds become
        1/0 (parity flags sample as 0/1); ``rate(counter)`` selects rate
        kind; a bare ``*_rate`` metric with no such series is sugar for
        ``rate(*_total)``."""
        for op in _OPS:
            if op in text:
                metric, _, rhs = text.partition(op)
                break
        else:
            raise ValueError(f"no comparison operator in SLO {text!r}")
        metric = metric.strip()
        rhs = rhs.strip().lower()
        threshold = {"true": 1.0, "false": 0.0}.get(rhs)
        if threshold is None:
            threshold = float(rhs)
        kind = "value"
        if metric.startswith("rate(") and metric.endswith(")"):
            metric = metric[5:-1].strip()
            kind = "rate"
        elif metric.endswith("_rate"):
            metric = metric[:-len("_rate")] + "_total"
            kind = "rate"
        return cls(name=name or text.strip(), metric=metric, op=op,
                   threshold=threshold, kind=kind, **overrides)

    # ------------------------------------------------------------ evaluation

    def _window_burn(self, store: TimeSeriesStore, name: str,
                     window_s: float, now: Optional[float]) -> Optional[dict]:
        """Violation fraction of one series over one window, or None when
        the window has too little data to judge."""
        if self.kind == "rate":
            rate = store.rate(name, window_s, now)
            if rate is None:
                return None
            bad = 0.0 if _compare(rate, self.op, self.threshold) else 1.0
            return {"frac": bad, "n": 2, "worst": rate}
        samples = store.values(name, window_s, now)
        if len(samples) < self.min_samples:
            return None
        vals = [v for _, v in samples]
        violations = [v for v in vals
                      if not _compare(v, self.op, self.threshold)]
        # "worst" = the sample farthest past the threshold; for == / !=
        # objectives any violator qualifies
        worst = max(violations, key=lambda v: abs(v - self.threshold)) \
            if violations else vals[-1]
        return {"frac": len(violations) / len(vals), "n": len(vals),
                "worst": worst}

    def evaluate(self, store: TimeSeriesStore,
                 now: Optional[float] = None) -> List[dict]:
        """Judge every series matching ``metric``: one result dict per
        series with fast/slow burn fractions and the multi-window breach
        verdict. Series with insufficient data report ``ok=True,
        judged=False`` — absence of evidence never pages."""
        matched = [n for n in store.names()
                   if fnmatch.fnmatchcase(n, self.metric)]
        out: List[dict] = []
        for name in matched:
            fast = self._window_burn(store, name, self.fast_window_s, now)
            slow = self._window_burn(store, name, self.slow_window_s, now)
            if fast is None:
                out.append({"slo": self.name, "series": name, "ok": True,
                            "judged": False})
                continue
            slow = slow or fast
            breach = fast["frac"] >= self.fast_burn \
                and slow["frac"] >= self.slow_burn
            out.append({
                "slo": self.name, "series": name, "ok": not breach,
                "judged": True, "kind": self.kind,
                "objective": f"{self.metric} {self.op} {_fmt_thresh(self.threshold)}",
                "fast_burn": round(fast["frac"], 4),
                "slow_burn": round(slow["frac"], 4),
                "worst": fast["worst"],
            })
        return out


def _fmt_thresh(v: float) -> str:
    return str(int(v)) if v == int(v) else f"{v:g}"


@dataclass
class SLOEngine:
    """Evaluates a set of specs each :meth:`check`; edge-triggers breach
    side effects (counter + telemetry + tagged flight dump)."""

    store: TimeSeriesStore
    specs: List[SLOSpec] = field(default_factory=list)
    registry: Optional[telemetry.MetricsRegistry] = None
    logger: Optional[telemetry.TelemetryLogger] = None
    recorder: Optional[flight_recorder.FlightRecorder] = None

    def __post_init__(self) -> None:
        if self.registry is None:
            self.registry = self.store.registry
        if self.logger is None:
            self.logger = telemetry.TelemetryLogger(namespace="slo")
        if self.recorder is None:
            self.recorder = flight_recorder.RECORDER
        #: (slo, series) pairs currently in breach (re-arm on recovery)
        self._breached: set = set()
        #: breach records emitted so far, newest last
        self.breaches: List[dict] = []

    # --------------------------------------------------------------- checks

    def _breach_trace(self, series: str) -> Dict[str, Optional[str]]:
        """Trace identity to tag the breach dump with: the WORST exemplar
        of the histogram behind the series when one was captured, else
        whatever trace is live on this thread (counter/gauge SLOs)."""
        hist = self.registry.find_histogram(series)
        if hist is not None and hist.worst_exemplar is not None:
            value, trace_id, span_id = hist.worst_exemplar
            return {"trace_id": trace_id, "span_id": span_id,
                    "exemplar_value_ms": value}
        from . import tracing   # late: tracing imports telemetry
        ctx = tracing.current()
        if ctx is not None:
            return {"trace_id": ctx.trace_id, "span_id": ctx.span_id}
        return {"trace_id": None}

    def check(self, now: Optional[float] = None) -> List[dict]:
        """Evaluate all specs against the store's current history and
        fire side effects for NEW breaches. Returns the new breach
        records (empty on a healthy tick). Call after ``store.tick()`` —
        the engine never samples on its own."""
        new: List[dict] = []
        for spec in self.specs:
            for result in spec.evaluate(self.store, now):
                key = (result["slo"], result["series"])
                if result["ok"]:
                    self._breached.discard(key)
                    continue
                if key in self._breached:
                    continue          # still breaching; already reported
                self._breached.add(key)
                trace = self._breach_trace(result["series"])
                record = {**result, **trace}
                self.registry.inc("slo_breach_total")
                self.logger.send_warning("slo_breach", **record)
                dump_path = self.recorder.dump(
                    f"slo:{spec.name}", extra={"slo": spec.name, **record})
                record["dump"] = dump_path
                self.breaches.append(record)
                new.append(record)
        return new

    def scorecard(self, now: Optional[float] = None) -> List[dict]:
        """Side-effect-free evaluation of every spec: the table healthz
        prints and bench.py embeds (one row per matched series; specs
        matching nothing report a single unjudged row so a typo'd metric
        pattern is visible, not silently green)."""
        rows: List[dict] = []
        for spec in self.specs:
            results = spec.evaluate(self.store, now)
            if not results:
                results = [{"slo": spec.name, "series": None, "ok": True,
                            "judged": False}]
            rows.extend(results)
        return rows


def default_slos() -> List[SLOSpec]:
    """The stack's standing objectives (docs/OBSERVABILITY.md table):
    ack latency under budget, zero apply stalls, digest parity holding,
    a quiet flight recorder, and zero replica-full sheds."""
    return [
        SLOSpec.parse("ack_p99_ms < 200", name="ack_latency"),
        SLOSpec.parse("rate(*apply_stalls) == 0", name="apply_stall_rate"),
        SLOSpec.parse("digest_parity == true", name="digest_parity",
                      min_samples=1),
        SLOSpec.parse("rate(flight_dump_total) == 0",
                      name="flight_dump_rate"),
        # replica-full shedding degrades device serving silently unless
        # it pages: any nonzero shed rate is a breach
        SLOSpec.parse("rate(*replica_sheds_total) == 0",
                      name="replica_shed_rate"),
        # capacity plane (ISSUE 19): the doc-memory budget must keep
        # ≥5% headroom; the gauge reads 1.0 when no budget is set, so
        # this only pages on processes that declared one. The breach
        # dump carries the capacity census (flight-recorder dump
        # context), so forensics see WHICH docs/owners ate the budget.
        SLOSpec.parse("memory_budget_headroom > 0.05",
                      name="memory_budget_headroom"),
        # read plane (ISSUE 20): bounded staleness — a delivered window
        # or replica catch-up must land within 2s of durability at p99.
        # The gauge only moves on processes that serve readers, so
        # write-only deployments never judge it.
        SLOSpec.parse("read_staleness_p99_s < 2",
                      name="read_staleness"),
    ]


def render_scorecard(rows: List[dict]) -> str:
    """Fixed-width text table of :meth:`SLOEngine.scorecard` rows."""
    out = [f"{'SLO':<20s} {'SERIES':<44s} {'STATE':<8s} "
           f"{'FAST':>6s} {'SLOW':>6s}  WORST"]
    for r in rows:
        state = "ok" if r["ok"] else "BREACH"
        if not r.get("judged"):
            state = "no-data"
        worst = r.get("worst")
        out.append(
            f"{r['slo']:<20s} {str(r.get('series')):<44s} {state:<8s} "
            f"{r.get('fast_burn', ''):>6} {r.get('slow_burn', ''):>6}  "
            f"{'' if worst is None else worst}")
    return "\n".join(out) + "\n"
