"""Time-series retention over the metrics registry: the health plane's memory.

Reference counterpart: the Prometheus scrape loop behind Routerlicious'
lag/latency alerting — a server is healthy not because a counter exists
but because its *trajectory* stays inside a target. PR 2 gave this stack
point-in-time metrics (``telemetry.MetricsRegistry``); this module adds
the notion of time: a :class:`TimeSeriesStore` samples
``REGISTRY.full_snapshot()`` on a clock **the caller ticks** (bench.py
phase boundaries, serving loops, tests — this module itself spawns no
thread; on live servers the ``server.opsd.OpsServer`` ticker is the
clock, everywhere else determinism and zero idle cost win),
keeps a bounded ring of history per metric, derives rates from counters
(reset-aware), and answers windowed percentile reads. ``utils.slo``
evaluates burn-rate targets over it; ``tools/healthz.py`` renders it as
a sparkline dashboard; bench.py exports it as JSONL evidence.

Sampling cost is one ``full_snapshot()`` (dict merges) plus one bounded
``deque.append`` per metric — safe to tick at phase boundaries of a hot
loop, not meant for per-op ticking.
"""

from __future__ import annotations

import json
import math
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

from . import telemetry

#: unicode sparkline ramp, low→high
_SPARK = "▁▂▃▄▅▆▇█"


class TimeSeriesStore:
    """Bounded per-metric history sampled from a ``MetricsRegistry``.

    Each :meth:`tick` appends ``(t, value)`` to every metric's ring
    (``capacity`` samples kept). Booleans sample as 0/1 so parity flags
    (``digest_parity``) are SLO-able like any other series. Metrics are
    classified via ``registry.full_snapshot_kinds()``: ``counter`` series
    get :meth:`rate` derivation (monotone deltas; a reset — engine
    restart, test isolation — contributes the post-reset value, never a
    negative), everything else is read as level.
    """

    def __init__(self, registry: Optional[telemetry.MetricsRegistry] = None,
                 capacity: int = 512, jsonl_path: Optional[str] = None):
        self.registry = registry if registry is not None \
            else telemetry.REGISTRY
        self.capacity = capacity
        #: metric -> deque of (t, value), oldest first
        self.series: Dict[str, deque] = {}
        #: metric -> "counter" | "gauge" | "quantile" (from the registry;
        #: frozen at first sight so a metric's class never flips mid-run)
        self.kinds: Dict[str, str] = {}
        self.jsonl_path = jsonl_path
        self.n_ticks = 0

    # ------------------------------------------------------------- sampling

    def tick(self, now: Optional[float] = None) -> float:
        """Sample the registry once; returns the sample time. The caller
        owns the clock — pass ``now`` for deterministic tests."""
        t = time.time() if now is None else float(now)
        snap = self.registry.full_snapshot()
        for k, kind in self.registry.full_snapshot_kinds().items():
            self.kinds.setdefault(k, kind)
        clean: Dict[str, float] = {}
        for k, v in snap.items():
            if isinstance(v, bool):
                v = 1.0 if v else 0.0
            if not isinstance(v, (int, float)):
                continue
            v = float(v)
            if math.isnan(v):
                continue
            clean[k] = v
            ring = self.series.get(k)
            if ring is None:
                ring = self.series[k] = deque(maxlen=self.capacity)
            ring.append((t, v))
        self.n_ticks += 1
        if self.jsonl_path:
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps(
                    {"t": t, "metrics": {k: clean[k]
                                         for k in sorted(clean)}}) + "\n")
        return t

    def ingest_sample(self, t: float, metrics: Dict[str, float],
                      kinds: Optional[Dict[str, str]] = None) -> None:
        """Append one externally-produced sample (the JSONL re-load path
        of ``tools/healthz.py``); ``kinds`` defaults to suffix inference."""
        for k, v in metrics.items():
            if isinstance(v, bool):
                v = 1.0 if v else 0.0
            if not isinstance(v, (int, float)) or math.isnan(float(v)):
                continue
            ring = self.series.get(k)
            if ring is None:
                ring = self.series[k] = deque(maxlen=self.capacity)
            ring.append((float(t), float(v)))
            if k not in self.kinds:
                self.kinds[k] = (kinds or {}).get(k) or _infer_kind(k)
        self.n_ticks += 1

    # -------------------------------------------------------------- reading

    def names(self) -> List[str]:
        return sorted(self.series)

    def values(self, name: str, window_s: Optional[float] = None,
               now: Optional[float] = None) -> List[Tuple[float, float]]:
        """(t, value) samples, oldest first, optionally clipped to the
        trailing ``window_s`` seconds (measured from ``now`` or the
        newest sample)."""
        ring = self.series.get(name)
        if not ring:
            return []
        samples = list(ring)
        if window_s is None:
            return samples
        end = samples[-1][0] if now is None else now
        return [s for s in samples if s[0] >= end - window_s]

    def latest(self, name: str) -> Optional[float]:
        ring = self.series.get(name)
        return ring[-1][1] if ring else None

    def rate(self, name: str, window_s: Optional[float] = None,
             now: Optional[float] = None) -> Optional[float]:
        """Counter → per-second rate over the window: sum of deltas /
        elapsed. Reset-aware: a sample BELOW its predecessor means the
        counter restarted from zero (engine rebuild, registry swap), so
        that step contributes the post-reset value — never a negative
        delta that would cancel real traffic. Needs >= 2 samples; None
        otherwise or for non-counter series."""
        if self.kinds.get(name, _infer_kind(name)) != "counter":
            return None
        samples = self.values(name, window_s, now)
        if len(samples) < 2:
            return None
        elapsed = samples[-1][0] - samples[0][0]
        if elapsed <= 0:
            return None
        total = 0.0
        for (_, prev), (_, cur) in zip(samples, samples[1:]):
            total += cur - prev if cur >= prev else cur
        return total / elapsed

    def window_summary(self, name: str, window_s: Optional[float] = None,
                       now: Optional[float] = None) -> Optional[dict]:
        """p50/p99/min/max/last/n over the window (levels verbatim;
        counters summarized on their per-step deltas would lie — use
        :meth:`rate` for those)."""
        samples = self.values(name, window_s, now)
        if not samples:
            return None
        vals = sorted(v for _, v in samples)
        n = len(vals)
        return {
            "n": n,
            "min": vals[0],
            "max": vals[-1],
            "p50": vals[n // 2],
            "p99": vals[min(n - 1, int(math.ceil(n * 0.99)) - 1)],
            "last": samples[-1][1],
        }

    # -------------------------------------------------------------- export

    def export_jsonl(self, path: str) -> int:
        """Write the whole retained history: one line per tick-time, the
        union of every metric's sample at that time. Returns the line
        count. (The incremental form is ``jsonl_path=`` at construction —
        one append per tick.)"""
        by_t: Dict[float, Dict[str, float]] = {}
        for name, ring in self.series.items():
            for t, v in ring:
                by_t.setdefault(t, {})[name] = v
        with open(path, "w") as f:
            for t in sorted(by_t):
                f.write(json.dumps(
                    {"t": t, "metrics": {k: by_t[t][k]
                                         for k in sorted(by_t[t])}}) + "\n")
        return len(by_t)

    @classmethod
    def from_jsonl(cls, path_or_lines: Any,
                   capacity: int = 512) -> "TimeSeriesStore":
        """Rebuild a store from an export (path or iterable of lines) —
        the offline half of ``tools/healthz.py``. Tolerates a torn tail
        the way every JSONL reader in this stack does."""
        store = cls(registry=telemetry.MetricsRegistry(), capacity=capacity)
        if isinstance(path_or_lines, str):
            with open(path_or_lines) as f:
                lines: Iterable[str] = f.readlines()
        else:
            lines = path_or_lines
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                break
            if isinstance(rec, dict) and "metrics" in rec:
                store.ingest_sample(rec.get("t", 0.0), rec["metrics"])
        return store

    # ------------------------------------------------------------ dashboard

    def render_sparklines(self, names: Optional[List[str]] = None,
                          width: int = 24, active_only: bool = True,
                          window_s: Optional[float] = None) -> str:
        """The text dashboard: one line per metric — sparkline of the
        last ``width`` samples, the latest value, and the derived rate
        for counters. ``active_only`` hides all-zero flat series (a full
        snapshot carries hundreds; the dashboard is for the ones that
        moved). Sorted by name; counters render their per-step deltas so
        a steadily-increasing total doesn't read as a ramp forever."""
        out: List[str] = []
        for name in (names if names is not None else self.names()):
            samples = self.values(name, window_s)
            if not samples:
                continue
            vals = [v for _, v in samples]
            kind = self.kinds.get(name, _infer_kind(name))
            if kind == "counter":
                deltas = [cur if cur < prev else cur - prev
                          for prev, cur in zip(vals, vals[1:])]
                plot = deltas if deltas else vals
            else:
                plot = vals
            if active_only and all(v == 0 for v in vals):
                continue
            tail = plot[-width:] if plot else [0.0]
            lo, hi = min(tail), max(tail)
            span = hi - lo
            marks = "".join(
                _SPARK[0] if span == 0 else
                _SPARK[min(len(_SPARK) - 1,
                           int((v - lo) / span * (len(_SPARK) - 1)))]
                for v in tail)
            line = f"{name:<48s} {marks:<{width}s} last={_fmt(vals[-1])}"
            r = self.rate(name, window_s)
            if r is not None:
                line += f" rate={_fmt(r)}/s"
            out.append(line)
        if not out:
            return "(no active series)\n"
        return "\n".join(out) + "\n"


def _infer_kind(name: str) -> str:
    """Suffix-based kind inference for series with no registry to ask
    (JSONL re-loads): the registry's naming conventions are stable enough
    to classify by shape."""
    if name.endswith(("_p50_ms", "_p99_ms")):
        return "quantile"
    if name.endswith(("_total", "_count", "_overflow")) or name.endswith(
            ("ops_ingested", "ops_applied", "ops_flushed", "flushes",
             "nacks", "appends", "compactions")):
        return "counter"
    return "gauge"


def _fmt(v: float) -> str:
    if v != v or v in (float("inf"), float("-inf")):
        return str(v)
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.3g}"
