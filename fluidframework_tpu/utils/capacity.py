"""Capacity plane (ISSUE 19): resident-doc census, device-memory
accounting, idle-age tracking.

ROADMAP items 1 (row migration) and 3 (doc eviction / lazy hydration)
both key off a signal that did not exist until this module: what a
resident doc *costs*, where the bytes live (host heap vs device HBM),
and how long each doc has been idle. The reference architecture
presumes exactly this — Routerlicious spins per-doc ordering state up
and down, which requires knowing what "down" would reclaim.

Three cooperating pieces:

* :class:`CapacityLedger` — a process-wide registry (module singleton
  :data:`LEDGER`, same pattern as ``telemetry.REGISTRY``) that
  memory-owning components register *pull providers* against. A
  provider is a zero-arg callable returning a :func:`report` dict
  (host bytes by category, device bytes, resident-doc count, optional
  per-doc heavy hitters). Registration holds weak references only —
  engines are born and die by the hundreds in tests and the ledger
  must never keep one alive. Components keep O(1) *incremental*
  byte counters at their growth points (interner payload appends,
  oplog tail appends, dedup inserts) so a census is a cheap walk of
  precomputed numbers, never an O(heap) traversal.

* device census — :func:`device_census` walks ``jax.live_arrays()``
  for the ground-truth HBM/backend-buffer total (the acceptance test
  pins ledger device totals to this number *exactly*) and reads the
  global pjit compile-cache occupancy through a guarded private-API
  probe (entry counts are available; jaxlib does not expose per-entry
  bytes — reported as ``None``, never guessed).

* :class:`IdleAgeTracker` — a monotonic last-touch clock per doc row.
  Both ingress doors touch it from their drain passes with ONE
  vectorized scatter per drained window (``last[rows] = now``) — no
  per-op cost. The census turns the clock into an idle-age histogram
  plus top-K coldest rows; coldest rows report the exact stamp of
  their last touch so "untouched since tick T" is provable.

Importing this module installs two flight-recorder dump-context
providers (``capacity_census`` and ``metrics_snapshot``) so every
crash/SLO-breach dump carries the memory picture for offline
forensics.
"""

from __future__ import annotations

import sys
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import flight_recorder as _flight
from . import telemetry as _telemetry

__all__ = [
    "CapacityLedger", "IdleAgeTracker", "LEDGER",
    "device_census", "compile_cache_stats", "device_nbytes",
    "report", "str_nbytes", "ndarray_nbytes", "interner_nbytes",
    "dict_nbytes", "list_nbytes", "record_nbytes",
    "idle_age_histogram",
]


# --------------------------------------------------------------------------
# host-side sizing helpers
# --------------------------------------------------------------------------
# Calibrated against CPython 3.10 x86-64 with tracemalloc (the census
# accuracy test holds the ledger within 15% of a tracemalloc delta, so
# these are measured amortized costs, not guesses).

#: amortized bytes per list slot (pointer + growth slack)
LIST_SLOT_BYTES = 8
#: amortized dict-table bytes per entry, EXCLUDING key/value objects
DICT_ENTRY_BYTES = 52
#: dict entry including two boxed ints (seq→seq maps, row caches)
INT_DICT_ENTRY_BYTES = 108
#: OrderedDict entry incl. boxed int key + small tuple value (the dedup
#: ledger's per-client window rows)
ODICT_ENTRY_BYTES = 195
#: empty OrderedDict container (one per (doc, client) dedup key)
ODICT_EMPTY_BYTES = 137
#: numpy array object header + base overhead beyond ``.nbytes``
NDARRAY_OVERHEAD_BYTES = 128
#: python object header of a small dataclass/record instance
RECORD_OVERHEAD_BYTES = 64


def str_nbytes(s: str) -> int:
    """Host bytes of one str object (exact for materialized strings)."""
    return sys.getsizeof(s)


def ndarray_nbytes(a: Any) -> int:
    """Host bytes of one numpy array: payload + object overhead."""
    nb = getattr(a, "nbytes", None)
    if nb is None:
        return 0
    return int(nb) + NDARRAY_OVERHEAD_BYTES


def list_nbytes(n_slots: int) -> int:
    """Amortized container bytes of a list with ``n_slots`` elements
    (element objects are charged separately by their own estimators)."""
    return 56 + LIST_SLOT_BYTES * int(n_slots)


def dict_nbytes(n_entries: int, per_entry: int = DICT_ENTRY_BYTES) -> int:
    """Amortized bytes of a dict with ``n_entries`` entries."""
    return 64 + per_entry * int(n_entries)


def interner_nbytes(n_entries: int, payload_bytes: int) -> int:
    """An interner table: id→payload list + payload→id dict around
    ``payload_bytes`` of accounted payload objects."""
    n = int(n_entries)
    return int(payload_bytes) + list_nbytes(n) + dict_nbytes(n)


def record_nbytes(rec: Any) -> int:
    """Host bytes of one oplog in-memory tail record.

    Counts numpy plane payloads (the dominant cost of columnar
    records) plus a constant object overhead. Deliberately does NOT
    walk str fields: sequenced-message texts are shared references
    into the interner payload table, which already charges them — a
    second charge here would double-count against tracemalloc."""
    total = RECORD_OVERHEAD_BYTES
    d = getattr(rec, "__dict__", None)
    if d is None and hasattr(rec, "__dataclass_fields__"):
        d = {f: getattr(rec, f, None) for f in rec.__dataclass_fields__}
    if d:
        total += dict_nbytes(len(d))
        for v in d.values():
            if isinstance(v, np.ndarray):
                total += ndarray_nbytes(v)
    return total


# --------------------------------------------------------------------------
# device census
# --------------------------------------------------------------------------

def device_nbytes(tree: Any) -> int:
    """Device-buffer bytes of one jax pytree (a store's ``state``):
    the sum of ``.nbytes`` over its jax-array leaves. Matches what
    ``jax.live_arrays()`` reports for the same buffers."""
    try:
        import jax
    except Exception:                                  # pragma: no cover
        return 0
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            total += int(leaf.nbytes)
    return total


def compile_cache_stats() -> Dict[str, Any]:
    """Global pjit executable-cache occupancy.

    Entry counts come from the private C++ cache objects (guarded —
    any jaxlib that renames them degrades to zeros, never raises).
    jaxlib exposes no per-entry byte size, so ``bytes`` is reported
    as ``None`` rather than a fabricated number."""
    entries = 0
    capacity = 0
    available = False
    try:
        from jax._src import pjit as _pjit
        for attr in ("_cpp_pjit_cache_fun_only",
                     "_cpp_pjit_cache_explicit_attributes"):
            cache = getattr(_pjit, attr, None)
            if cache is None:
                continue
            entries += int(cache.size())
            capacity += int(cache.capacity())
            available = True
    except Exception:
        available = False
    return {"available": available, "entries": entries,
            "capacity": capacity, "bytes": None}


def device_census() -> Dict[str, Any]:
    """Ground-truth device accounting: every live jax array's nbytes
    (what the ledger's per-engine device charges must sum to) plus
    compile-cache occupancy."""
    try:
        import jax
        arrs = jax.live_arrays()
    except Exception:                                  # pragma: no cover
        return {"available": False, "total_bytes": 0, "live_arrays": 0,
                "compile_cache": compile_cache_stats()}
    return {
        "available": True,
        "total_bytes": int(sum(int(a.nbytes) for a in arrs)),
        "live_arrays": len(arrs),
        "compile_cache": compile_cache_stats(),
    }


# --------------------------------------------------------------------------
# provider report shape
# --------------------------------------------------------------------------

def report(host: Optional[Dict[str, int]] = None,
           device: Optional[Dict[str, int]] = None,
           docs: int = 0,
           heaviest: Optional[List[Tuple[Any, int]]] = None,
           ) -> Dict[str, Any]:
    """Canonical provider return shape. ``host``/``device`` map
    category → bytes (categories are free-form: ``interner``,
    ``oplog_tail``, ``dedup``, ``state`` ...); ``docs`` is the
    resident-doc count this owner holds; ``heaviest`` is an optional
    pre-ranked ``[(doc_id, bytes), ...]`` for the top-K census."""
    return {"host": dict(host or {}), "device": dict(device or {}),
            "docs": int(docs), "heaviest": list(heaviest or [])}


# --------------------------------------------------------------------------
# idle-age tracking
# --------------------------------------------------------------------------

class IdleAgeTracker:
    """Monotonic last-touch clock per doc row.

    ``touch(rows)`` is ONE numpy scatter (``last[rows] = now``) — the
    drain passes call it once per window with the unique-row vector
    they already compute for the hot-doc sketch, so idle tracking adds
    no per-op cost. Rows never touched are not resident (stamp < 0).

    The tracker grows on demand (``touch`` ensures capacity), so the
    doors do not need to know engine capacity up front."""

    def __init__(self, capacity: int = 0,
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._last = np.full(max(0, int(capacity)), -1.0, dtype=np.float64)
        self.touches = 0          # windows observed, not ops

    def ensure(self, n: int) -> None:
        if n > self._last.shape[0]:
            grown = np.full(max(n, 2 * self._last.shape[0] or 64), -1.0,
                            dtype=np.float64)
            grown[:self._last.shape[0]] = self._last
            self._last = grown

    def touch(self, rows: np.ndarray,
              now: Optional[float] = None) -> None:
        """Stamp ``rows`` (array-like of row indices) as touched now.
        One vectorized scatter; safe under the GIL without a lock."""
        rows = np.asarray(rows, dtype=np.int64)
        if rows.size == 0:
            return
        self.ensure(int(rows.max()) + 1)
        self._last[rows] = self._clock() if now is None else now
        self.touches += 1

    def last_touch(self, row: int) -> Optional[float]:
        """Monotonic stamp of the row's last touch (None = never)."""
        if 0 <= row < self._last.shape[0] and self._last[row] >= 0.0:
            return float(self._last[row])
        return None

    def resident_rows(self) -> np.ndarray:
        return np.nonzero(self._last >= 0.0)[0]

    def ages(self, now: Optional[float] = None) -> np.ndarray:
        """Idle age in seconds of every touched row (float64 vector)."""
        now = self._clock() if now is None else now
        touched = self._last[self._last >= 0.0]
        return now - touched

    def coldest(self, k: int = 8,
                now: Optional[float] = None) -> List[Dict[str, float]]:
        """Top-``k`` longest-idle rows with the exact stamp of their
        last touch — "untouched since tick T", provably."""
        now = self._clock() if now is None else now
        rows = self.resident_rows()
        if rows.size == 0:
            return []
        stamps = self._last[rows]
        order = np.argsort(stamps, kind="stable")[:max(0, int(k))]
        return [{"row": int(rows[i]), "last_touch": float(stamps[i]),
                 "idle_s": float(now - stamps[i])} for i in order]

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        ages = self.ages(now)
        out: Dict[str, Any] = {"resident_rows": int(ages.size),
                               "touch_windows": int(self.touches)}
        if ages.size:
            out.update(
                idle_p50_s=float(np.percentile(ages, 50)),
                idle_p99_s=float(np.percentile(ages, 99)),
                idle_max_s=float(ages.max()))
        return out


def idle_age_histogram(ages_s: np.ndarray) -> _telemetry.Histogram:
    """A point-in-time ``Histogram`` of idle ages (seconds), filled
    with one vectorized pass — the ``doc_idle_age_s`` metric family is
    a distribution snapshot, rebuilt at each census (idle age is a
    level, not an accumulating stream; re-observing resident rows into
    a cumulative histogram every tick would inflate it)."""
    h = _telemetry.Histogram()
    ages = np.asarray(ages_s, dtype=np.float64)
    h.n = int(ages.size)
    h.sum_ms = float(ages.sum()) if ages.size else 0.0
    if ages.size:
        idx = np.searchsorted(np.asarray(h.bounds), ages, side="left")
        counts = np.bincount(idx, minlength=len(h.counts))
        h.counts = [int(c) for c in counts]
    return h


# --------------------------------------------------------------------------
# the ledger
# --------------------------------------------------------------------------

class CapacityLedger:
    """Process-wide capacity accounting: pull providers + idle
    trackers, rolled up into one census.

    Providers register with :meth:`register` (weakly — bound methods
    go through ``weakref.WeakMethod``; a collected owner silently
    drops out of the census, mirroring ``MetricsRegistry.attach``).
    """

    def __init__(self):
        self._providers: Dict[str, Any] = {}     # key -> weak callable
        self._idle: Dict[str, Any] = {}          # key -> weak tracker ref
        self._idle_resolvers: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self.budget_bytes: Optional[int] = None
        # cached device walk: a 1 Hz ops ticker must not pay a full
        # live-array walk per beat (scrape-overhead bound, PR 13)
        self._device_cache: Optional[Dict[str, Any]] = None
        self._device_cache_t = 0.0

    # ---------------------------------------------------------- providers

    @staticmethod
    def _weak(fn: Callable[..., Any]) -> Callable[[], Optional[Any]]:
        """A resolver returning the live callable or None. Bound
        methods must not be kept alive through their __self__."""
        if hasattr(fn, "__self__") and fn.__self__ is not None:
            wm = weakref.WeakMethod(fn)
            return lambda: wm()
        return lambda: fn

    def register(self, owner: str,
                 provider: Callable[[], Dict[str, Any]]) -> str:
        """Register a pull provider under ``owner`` (auto-suffixed on
        collision with a still-live registration). Returns the key."""
        with self._lock:
            base, i, key = owner, 1, owner
            while key in self._providers \
                    and self._providers[key]() is not None:
                i += 1
                key = f"{base}{i}"
            self._providers[key] = self._weak(provider)
            return key

    def unregister(self, key: str) -> None:
        with self._lock:
            self._providers.pop(key, None)

    def add_idle_tracker(self, owner: str, tracker: IdleAgeTracker,
                         row_doc_id: Optional[Callable[[int], Any]] = None
                         ) -> str:
        """Attach an idle tracker (weakly). ``row_doc_id`` optionally
        resolves row index → doc id for the coldest-doc census."""
        with self._lock:
            base, i, key = owner, 1, owner
            while key in self._idle and self._idle[key]() is not None:
                i += 1
                key = f"{base}{i}"
            self._idle[key] = weakref.ref(tracker)
            if row_doc_id is not None:
                self._idle_resolvers[key] = self._weak(row_doc_id)
            return key

    def set_budget(self, nbytes: Optional[int]) -> None:
        """Set (or clear) the process doc-memory budget the
        ``memory_budget_headroom`` SLO judges against."""
        self.budget_bytes = None if nbytes is None else int(nbytes)

    # -------------------------------------------------------------- census

    def _live_providers(self) -> List[Tuple[str, Callable]]:
        out = []
        with self._lock:
            for key in list(self._providers):
                fn = self._providers[key]()
                if fn is None:
                    del self._providers[key]
                else:
                    out.append((key, fn))
        return out

    def _live_idle(self) -> List[Tuple[str, IdleAgeTracker,
                                       Optional[Callable]]]:
        out = []
        with self._lock:
            for key in list(self._idle):
                tr = self._idle[key]()
                if tr is None:
                    del self._idle[key]
                    self._idle_resolvers.pop(key, None)
                else:
                    res = self._idle_resolvers.get(key)
                    out.append((key, tr, res() if res else None))
        return out

    def device_census_cached(self, ttl_s: float = 5.0) -> Dict[str, Any]:
        now = time.monotonic()
        if self._device_cache is None \
                or now - self._device_cache_t > ttl_s:
            self._device_cache = device_census()
            self._device_cache_t = now
        return self._device_cache

    def census(self, top_k: int = 8, device: bool = True,
               device_ttl_s: float = 0.0) -> Dict[str, Any]:
        """One full capacity census.

        Host/device/doc totals by owner and category from every live
        provider, the ground-truth device walk (``device_ttl_s > 0``
        serves it from the tick cache), idle-age summaries per
        tracker, and the top-K heaviest / coldest docs."""
        t0 = time.perf_counter()
        host_by_owner: Dict[str, int] = {}
        dev_by_owner: Dict[str, int] = {}
        host_by_cat: Dict[str, int] = {}
        docs_by_owner: Dict[str, int] = {}
        heaviest: List[Dict[str, Any]] = []
        errors: Dict[str, str] = {}
        for key, fn in self._live_providers():
            try:
                rep = fn()
            except Exception as e:   # census must never take a plane down
                errors[key] = repr(e)
                continue
            h = sum(int(v) for v in rep.get("host", {}).values())
            d = sum(int(v) for v in rep.get("device", {}).values())
            host_by_owner[key] = h
            dev_by_owner[key] = d
            docs_by_owner[key] = int(rep.get("docs", 0))
            for cat, v in rep.get("host", {}).items():
                host_by_cat[cat] = host_by_cat.get(cat, 0) + int(v)
            for doc, b in rep.get("heaviest", []):
                heaviest.append({"owner": key, "doc": doc,
                                 "bytes": int(b)})
        heaviest.sort(key=lambda r: r["bytes"], reverse=True)
        host_total = sum(host_by_owner.values())
        dev_total = sum(dev_by_owner.values())

        idle: Dict[str, Any] = {}
        coldest: List[Dict[str, Any]] = []
        for key, tr, resolve in self._live_idle():
            idle[key] = tr.snapshot()
            for row in tr.coldest(top_k):
                row = dict(row, owner=key)
                if resolve is not None:
                    try:
                        row["doc"] = resolve(row["row"])
                    except Exception:
                        pass
                coldest.append(row)
        coldest.sort(key=lambda r: r["idle_s"], reverse=True)

        out: Dict[str, Any] = {
            "host": {"total_bytes": int(host_total),
                     "by_owner": host_by_owner,
                     "by_category": host_by_cat},
            "device": {"total_bytes": int(dev_total),
                       "by_owner": dev_by_owner},
            "docs": {"resident": sum(docs_by_owner.values()),
                     "by_owner": docs_by_owner},
            "idle": idle,
            "top": {"heaviest": heaviest[:max(0, int(top_k))],
                    "coldest": coldest[:max(0, int(top_k))]},
            "budget_bytes": self.budget_bytes,
            "headroom": self.headroom(host_total + dev_total),
        }
        if device:
            out["device"]["walk"] = (
                self.device_census_cached(device_ttl_s) if device_ttl_s
                else device_census())
        if errors:
            out["errors"] = errors
        out["census_ms"] = (time.perf_counter() - t0) * 1e3
        return out

    def headroom(self, used_bytes: Optional[int] = None) -> float:
        """Fraction of the budget still free, clamped to [0, 1]; 1.0
        when no budget is set (headroom without a budget never pages)."""
        if not self.budget_bytes:
            return 1.0
        if used_bytes is None:
            c = self.census(top_k=0, device=False)
            used_bytes = c["host"]["total_bytes"] \
                + c["device"]["total_bytes"]
        free = 1.0 - float(used_bytes) / float(self.budget_bytes)
        return min(1.0, max(0.0, free))

    # -------------------------------------------------------------- gauges

    def publish_gauges(self,
                       registry: Optional[Any] = None,
                       device_ttl_s: float = 5.0) -> Dict[str, Any]:
        """Publish the metric families onto ``registry`` (default: the
        process REGISTRY): ``doc_resident_bytes`` (host charges),
        ``device_buffer_bytes`` (ledger device charges),
        ``device_live_array_bytes`` / ``compile_cache_entries`` (the
        ground-truth walk, tick-cached), ``resident_docs_total``,
        ``doc_memory_budget_bytes`` + ``memory_budget_headroom``, and
        the ``doc_idle_age_s`` distribution snapshot. Returns the
        census it published from."""
        reg = registry if registry is not None else _telemetry.REGISTRY
        c = self.census(top_k=0, device=True, device_ttl_s=device_ttl_s)
        reg.set_gauge("doc_resident_bytes", float(c["host"]["total_bytes"]))
        reg.set_gauge("device_buffer_bytes",
                      float(c["device"]["total_bytes"]))
        walk = c["device"].get("walk") or {}
        if walk.get("available"):
            reg.set_gauge("device_live_array_bytes",
                          float(walk["total_bytes"]))
            reg.set_gauge("compile_cache_entries",
                          float(walk["compile_cache"]["entries"]))
        reg.set_gauge("resident_docs_total", float(c["docs"]["resident"]))
        if self.budget_bytes:
            reg.set_gauge("doc_memory_budget_bytes",
                          float(self.budget_bytes))
        reg.set_gauge("memory_budget_headroom", float(c["headroom"]))
        ages: List[np.ndarray] = []
        for _key, tr, _res in self._live_idle():
            a = tr.ages()
            if a.size:
                ages.append(a)
        if ages:
            reg.histograms["doc_idle_age_s"] = idle_age_histogram(
                np.concatenate(ages))
        return c


#: the process-wide ledger (engines, oplogs, doors all register here)
LEDGER = CapacityLedger()


def _census_for_dump() -> Dict[str, Any]:
    """Compact census for flight-dump headers (no device walk cache —
    dumps are rare and want fresh truth; numpy scalars coerced by the
    dump's _jsonable)."""
    return LEDGER.census(top_k=4, device=True)


_flight.add_dump_context("capacity_census", _census_for_dump)
_flight.add_dump_context("metrics_snapshot",
                         lambda: _telemetry.REGISTRY.snapshot())
