"""Cross-cutting utilities: telemetry (§2.15/§5.1), config/feature gates
(§5.6)."""

from .config import ConfigProvider
from .telemetry import (
    ERROR,
    GENERIC,
    PERFORMANCE,
    BufferSink,
    Histogram,
    MetricsCollector,
    PerformanceEvent,
    SampledTelemetry,
    TelemetryLogger,
    console_sink,
)

__all__ = [
    "ConfigProvider",
    "ERROR",
    "GENERIC",
    "PERFORMANCE",
    "BufferSink",
    "Histogram",
    "MetricsCollector",
    "PerformanceEvent",
    "SampledTelemetry",
    "TelemetryLogger",
    "console_sink",
]
