"""Config provider / feature-gate system.

Reference counterpart: ``IConfigProviderBase`` + the ``Fluid.*`` feature-gate
keys monitored through ``loggerToMonitoringContext`` (SURVEY.md §5.6; mount
empty). Layered key→value lookup with typed getters: explicit overrides win
over environment variables (``FLUID_TPU_<KEY with dots as __>``) win over a
JSON file, falling back to the caller's default — the "stage-roll a risky
behavior without a release" escape hatch the reference uses feature gates
for.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional


class ConfigProvider:
    def __init__(self, overrides: Optional[Dict[str, Any]] = None,
                 json_path: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None,
                 env_prefix: str = "FLUID_TPU_"):
        self._overrides = dict(overrides or {})
        self._env = env if env is not None else dict(os.environ)
        self._env_prefix = env_prefix
        self._file: Dict[str, Any] = {}
        if json_path and os.path.exists(json_path):
            with open(json_path) as f:
                self._file = json.load(f)

    # ----------------------------------------------------------- raw lookup

    def raw(self, key: str) -> Optional[Any]:
        if key in self._overrides:
            return self._overrides[key]
        env_key = self._env_prefix + key.replace(".", "__")
        if env_key in self._env:
            return self._env[env_key]
        return self._file.get(key)

    def set(self, key: str, value: Any) -> None:
        """Runtime override (highest precedence)."""
        self._overrides[key] = value

    # -------------------------------------------------------- typed getters

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.raw(key)
        if v is None:
            return default
        if isinstance(v, bool):
            return v
        return str(v).strip().lower() in ("1", "true", "yes", "on")

    def get_int(self, key: str, default: int = 0) -> int:
        v = self.raw(key)
        if v is None:
            return default
        try:
            return int(v)
        except (TypeError, ValueError):
            return default

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self.raw(key)
        if v is None:
            return default
        try:
            return float(v)
        except (TypeError, ValueError):
            return default

    def get_str(self, key: str, default: str = "") -> str:
        v = self.raw(key)
        return default if v is None else str(v)
