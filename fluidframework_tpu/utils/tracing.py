"""End-to-end op tracing: per-batch span trees across the whole stack.

Reference counterpart: the distributed-tracing discipline behind the
reference service's correlation ids (Alfred stamps a correlation id per
socket message; every lambda logs against it) — here grown into real
spans: a :class:`TraceContext` (trace id + span id) is attached to an op
batch at the client outbox, rides the wire (op frames / raw-log records /
``SequencedDocumentMessage.trace``) through ingress, Deli sequencing,
serving apply, and the broadcast ack, and every layer opens a host-timed
span (built on ``telemetry.PerformanceEvent``) under its parent. The
result is a per-batch span tree — outbox → wire → deli → apply → ack —
exportable as Chrome trace-event JSON (``chrome://tracing`` / Perfetto)
and renderable as text by ``tools.trace_viewer``.

Spans are recorded into a process-wide bounded ring (:data:`TRACER`);
within a process, parentage flows implicitly through a thread-local
context stack, so nested layers need no plumbing; across process/socket
hops the context is serialized with :meth:`TraceContext.to_wire` (a
2-key dict) and re-attached with :func:`attach` on the far side.

Span start/end events also flow through the tracer's
:class:`~fluidframework_tpu.utils.telemetry.TelemetryLogger`, which means
they land in the crash flight recorder — a dump shows the spans in
flight when a faultpoint fired.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

from .telemetry import PerformanceEvent, TelemetryLogger


class TraceContext:
    """One node of a span tree: (trace_id, span_id). Serializes to a
    2-key dict for wire frames and log records."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: int):
        self.trace_id = trace_id
        self.span_id = span_id

    def to_wire(self) -> dict:
        return {"tid": self.trace_id, "sid": self.span_id}

    @staticmethod
    def from_wire(d: Any) -> Optional["TraceContext"]:
        if isinstance(d, dict) and "tid" in d and "sid" in d:
            return TraceContext(d["tid"], d["sid"])
        return None

    def __repr__(self) -> str:
        return f"TraceContext({self.trace_id}, {self.span_id})"


class Span:
    """A timed span, used as a context manager. While entered, it is the
    thread's current context: child spans and ``current_wire()`` parent
    to it. Timing is delegated to ``PerformanceEvent`` (the span emits
    the reference ``_start``/``_end``/``_cancel`` telemetry events)."""

    def __init__(self, tracer: "Tracer", name: str, ctx: TraceContext,
                 parent_id: Optional[int], args: Dict[str, Any]):
        self.tracer = tracer
        self.name = name
        self.ctx = ctx
        self.parent_id = parent_id
        self.args = args
        self._pe = PerformanceEvent(
            tracer.logger, name,
            {"trace_id": ctx.trace_id, "span_id": ctx.span_id})
        self._ts_us: Optional[float] = None

    def annotate(self, **args: Any) -> "Span":
        """Attach args after entry (device-dispatch counters measured
        inside the span)."""
        self.args.update(args)
        return self

    def __enter__(self) -> "Span":
        self._ts_us = time.time() * 1e6
        self._pe.__enter__()
        self.tracer._push(self.ctx)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.tracer._pop()
        self._pe.__exit__(exc_type, exc, tb)
        event = {
            "name": self.name,
            "trace_id": self.ctx.trace_id,
            "span_id": self.ctx.span_id,
            "parent_id": self.parent_id,
            "ts": self._ts_us,
            "dur": (self._pe.duration_ms or 0.0) * 1e3,  # µs
            "tid": threading.get_ident(),
            "args": self.args,
        }
        if exc is not None:
            event["error"] = repr(exc)
        self.tracer._record(event)


class _NullSpan:
    """Disabled-tracer stand-in: same surface, no recording."""

    ctx = None
    args: Dict[str, Any] = {}

    def annotate(self, **_args: Any) -> "_NullSpan":
        return self

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *_exc) -> None:
        pass


_NULL = _NullSpan()


class Tracer:
    """Process-wide span recorder: a bounded ring of completed span
    events plus a thread-local current-context stack."""

    def __init__(self, capacity: int = 65536):
        self.enabled = True
        self.capacity = capacity
        self._events: deque = deque(maxlen=capacity)
        self._span_ids = itertools.count(1)
        self._trace_ids = itertools.count(1)
        self._local = threading.local()
        #: spans mirror their start/end through this logger (no sink by
        #: default — events still reach the flight recorder)
        self.logger = TelemetryLogger(None, "trace")
        self._sample_counters: Dict[str, int] = {}

    # ----------------------------------------------------------- id issue

    def new_trace_id(self) -> str:
        return f"{os.getpid():x}.{next(self._trace_ids):x}"

    # ---------------------------------------------------- context plumbing

    def _stack(self) -> List[TraceContext]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _push(self, ctx: TraceContext) -> None:
        self._stack().append(ctx)

    def _pop(self) -> None:
        stack = self._stack()
        if stack:
            stack.pop()

    def current(self) -> Optional[TraceContext]:
        stack = self._stack()
        return stack[-1] if stack else None

    # ------------------------------------------------------------ spanning

    def span(self, name: str, parent: Optional[Any] = None,
             **args: Any) -> Any:
        """Open a span. ``parent`` may be a :class:`TraceContext`, a wire
        dict (``{"tid", "sid"}``), or None — None parents to the thread's
        current span, or starts a new trace at the root."""
        if not self.enabled:
            return _NULL
        if parent is None:
            parent = self.current()
        elif not isinstance(parent, TraceContext):
            parent = TraceContext.from_wire(parent) or self.current()
        if parent is None:
            ctx = TraceContext(self.new_trace_id(), next(self._span_ids))
            parent_id = None
        else:
            ctx = TraceContext(parent.trace_id, next(self._span_ids))
            parent_id = parent.span_id
        return Span(self, name, ctx, parent_id, args)

    def maybe_root_span(self, name: str, every: int = 1024,
                        **args: Any) -> Any:
        """Sampled root span for server-only hot paths (no client trace
        upstream): opens a real span when a trace is already current, or
        on every ``every``-th call — so bench/serving loops yield a few
        representative timelines without per-op overhead."""
        if not self.enabled:
            return _NULL
        if self.current() is not None:
            return self.span(name, **args)
        n = self._sample_counters.get(name, 0)
        self._sample_counters[name] = n + 1
        if n % every == 0:
            return self.span(name, **args)
        return _NULL

    # ----------------------------------------------------------- recording

    def _record(self, event: dict) -> None:
        self._events.append(event)

    def record_complete(self, name: str, dur_ms: float,
                        parent: Optional[Any] = None,
                        **args: Any) -> Optional[TraceContext]:
        """Record an already-measured span (hot batch paths that time
        themselves): one ring append, no context-manager overhead. The
        span is stamped as ending now. Returns its context (or None when
        disabled)."""
        if not self.enabled:
            return None
        if parent is None:
            parent = self.current()
        elif not isinstance(parent, TraceContext):
            parent = TraceContext.from_wire(parent) or self.current()
        if parent is None:
            ctx = TraceContext(self.new_trace_id(), next(self._span_ids))
            parent_id = None
        else:
            ctx = TraceContext(parent.trace_id, next(self._span_ids))
            parent_id = parent.span_id
        now_us = time.time() * 1e6
        self._record({
            "name": name, "trace_id": ctx.trace_id,
            "span_id": ctx.span_id, "parent_id": parent_id,
            "ts": now_us - dur_ms * 1e3, "dur": dur_ms * 1e3,
            "tid": threading.get_ident(), "args": args,
        })
        return ctx

    def events(self, trace_id: Optional[str] = None) -> List[dict]:
        evs = list(self._events)
        if trace_id is not None:
            evs = [e for e in evs if e["trace_id"] == trace_id]
        return evs

    def trace_ids(self) -> List[str]:
        """Distinct trace ids in the ring, oldest first."""
        seen: Dict[str, None] = {}
        for e in self._events:
            seen.setdefault(e["trace_id"], None)
        return list(seen)

    def clear(self) -> None:
        self._events.clear()

    # ------------------------------------------------------------- export

    def export_chrome(self, path: Optional[str] = None,
                      trace_id: Optional[str] = None) -> dict:
        """Chrome trace-event JSON (``"ph": "X"`` complete events, µs
        timestamps) — loadable in chrome://tracing / Perfetto and by
        ``tools.trace_viewer``. Writes to ``path`` when given."""
        doc = {"traceEvents": [chrome_event(e)
                               for e in self.events(trace_id)]}
        if path is not None:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc


def chrome_event(e: dict) -> dict:
    return {
        "ph": "X", "name": e["name"], "cat": "op",
        "ts": e["ts"], "dur": e["dur"],
        "pid": os.getpid(), "tid": e["tid"],
        "args": {"trace_id": e["trace_id"], "span_id": e["span_id"],
                 "parent_id": e["parent_id"],
                 **{k: _arg(v) for k, v in e.get("args", {}).items()},
                 **({"error": e["error"]} if "error" in e else {})},
    }


def _arg(v: Any) -> Any:
    return v if isinstance(v, (int, float, str, bool, type(None))) \
        else repr(v)


#: the process tracer — all layers record here
TRACER = Tracer()


def span(name: str, parent: Optional[Any] = None, **args: Any) -> Any:
    return TRACER.span(name, parent, **args)


def current() -> Optional[TraceContext]:
    return TRACER.current()


def current_wire() -> Optional[dict]:
    """The current context as a wire dict, or None — what gets stamped
    into frames / raw-log records at a serialization boundary."""
    ctx = TRACER.current()
    return ctx.to_wire() if ctx is not None else None


class attach:
    """``with attach(wire_dict): ...`` — re-establish a deserialized
    context as the thread's current (the receiving side of a process or
    socket hop). A None/invalid dict is a no-op."""

    def __init__(self, wire: Any):
        self.ctx = TraceContext.from_wire(wire)

    def __enter__(self) -> Optional[TraceContext]:
        if self.ctx is not None:
            TRACER._push(self.ctx)
        return self.ctx

    def __exit__(self, *_exc) -> None:
        if self.ctx is not None:
            TRACER._pop()


def set_enabled(flag: bool) -> None:
    TRACER.enabled = flag


def span_tree(events: Iterable[dict], trace_id: Optional[str] = None
              ) -> List[dict]:
    """Nest flat span events into a tree: each node gets a ``children``
    list, roots returned in start order. Accepts tracer events or the
    ``args``-carrying Chrome form (``tools.trace_viewer`` renders both)."""
    nodes: Dict[int, dict] = {}
    flat: List[dict] = []
    for e in events:
        a = e.get("args") or {}
        node = {
            "name": e["name"],
            "trace_id": e.get("trace_id", a.get("trace_id")),
            "span_id": e.get("span_id", a.get("span_id")),
            "parent_id": e.get("parent_id", a.get("parent_id")),
            "ts": e.get("ts", 0.0),
            "dur": e.get("dur", 0.0),
            "args": {k: v for k, v in a.items()
                     if k not in ("trace_id", "span_id", "parent_id")},
            "children": [],
        }
        if trace_id is not None and node["trace_id"] != trace_id:
            continue
        flat.append(node)
        if node["span_id"] is not None:
            nodes[node["span_id"]] = node
    roots: List[dict] = []
    for node in flat:
        parent = nodes.get(node["parent_id"]) \
            if node["parent_id"] is not None else None
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    for n in nodes.values():
        n["children"].sort(key=lambda c: c["ts"])
    roots.sort(key=lambda c: c["ts"])
    return roots
