"""Atomic file writes: tmp + fsync + rename.

Checkpoint persistence (Deli snapshots, service state) must never leave
a HALF-written file where the old checkpoint used to be — a crash mid-
write would otherwise destroy the only recovery anchor. POSIX rename is
atomic within a filesystem, so: write to a sibling tmp file, fsync,
rename over the target. A crash before the rename leaves the previous
checkpoint intact (plus a stray ``.tmp`` that the next write replaces).

The ``checkpoint.mid_write`` fault point sits between the tmp write and
the rename — exactly the window a chaos drill kills to prove the old
file survives.
"""

from __future__ import annotations

import json
import os
import tempfile

from .faultpoints import SITE_CHECKPOINT_MID_WRITE, fault_point


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` atomically (all-or-nothing)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        fault_point(SITE_CHECKPOINT_MID_WRITE, path=path, tmp=tmp)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path: str, obj) -> None:
    atomic_write_bytes(path, json.dumps(obj).encode())


def read_json(path: str):
    with open(path, "rb") as f:
        return json.loads(f.read())
