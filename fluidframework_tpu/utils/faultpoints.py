"""Named fault-injection points for the server stack.

The chaos harness (``testing.chaos``) needs to kill the pipeline at
*arbitrary, named* places — mid-sequencing, between a durable append and
its spill write, between a summary upload and its ack — and the
production code needs to pay nothing for that capability when no drill
is running. This module is the contract between the two: server code
drops a ``fault_point("site.name")`` call at each interesting boundary
(one global ``is None`` check when disarmed), and a drill installs a
:class:`testing.chaos.FaultPlan` that decides — per site, per hit —
whether to crash (:class:`CrashInjected`), stall, or pass through.

Sites are registered at import time of the module that hosts them, so
``registered_sites()`` documents the full injection surface and drills
can assert they cover it.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, Optional, Set

#: every site name ever declared via :func:`declare_site` — the
#: discoverable injection surface (drills sweep it; reviews audit it).
_SITES: Set[str] = set()

_lock = threading.Lock()
_plan = None  # the installed plan, or None (disarmed)


class CrashInjected(RuntimeError):
    """Raised by an armed fault plan to simulate a process kill at a
    fault point. Carries the site name; drills catch it and run the
    recovery path exactly as a restarted process would."""

    def __init__(self, site: str):
        super().__init__(f"injected crash at {site}")
        self.site = site


def declare_site(name: str) -> str:
    """Register a site name (idempotent); returns it so hosts can write
    ``SITE_X = declare_site("x")`` and pass the constant around."""
    with _lock:
        _SITES.add(name)
    return name


def registered_sites() -> Set[str]:
    with _lock:
        return set(_SITES)


def install(plan) -> None:
    """Arm ``plan`` globally. Only one plan at a time — nested drills
    would make hit counts meaningless."""
    global _plan
    with _lock:
        if _plan is not None:
            raise RuntimeError("a fault plan is already installed")
        _plan = plan


def uninstall() -> None:
    global _plan
    with _lock:
        _plan = None


def active_plan():
    return _plan


def fault_point(site: str, **ctx) -> None:
    """The hook server code calls. Disarmed: one global read, no other
    work. Armed: the plan decides (crash / stall / nothing). When the
    plan FIRES (raises — an injected crash), the flight recorder dumps
    its ring to JSONL first, so every simulated kill leaves the same
    structured evidence a production crash handler would."""
    plan = _plan
    if plan is not None:
        try:
            plan.hit(site, **ctx)
        except BaseException as e:
            from . import flight_recorder
            flight_recorder.note(
                "faultpoint_fired", site=site, error=repr(e),
                **{k: v for k, v in ctx.items()
                   if isinstance(v, (int, float, str, bool))})
            try:
                flight_recorder.dump(f"faultpoint:{site}",
                                     extra={"site": site})
            except OSError:
                pass  # evidence is best-effort; the crash must proceed
            raise


class armed:
    """``with armed(plan): ...`` — install for the block, always
    uninstall (even when the block exits via CrashInjected)."""

    def __init__(self, plan):
        self.plan = plan

    def __enter__(self):
        install(self.plan)
        return self.plan

    def __exit__(self, *_exc):
        uninstall()
        return False


class ProbabilisticPlan:
    """Repeat-fire fault plan: each armed site crashes with probability
    ``p`` on every hit, drawn from one seeded rng so a soak run replays
    exactly. Unlike :class:`testing.chaos.FaultPlan` (one-shot budgets:
    "crash on the Nth hit"), this plan never exhausts — it models a
    flaky fleet rather than a scripted kill.

    ``arm(site, p)`` may be called before or after install; ``disarm``
    removes one site. ``fires`` counts injected crashes per site so
    drills can assert coverage.
    """

    def __init__(self, rng: Optional[random.Random] = None):
        self.rng = rng or random.Random()
        self._p: Dict[str, float] = {}
        self._stall: Dict[str, tuple] = {}   # site → (p, seconds)
        self.fires: Dict[str, int] = {}
        self.stalls: Dict[str, int] = {}
        self._lock = threading.Lock()

    def arm(self, site: str, p: float = 0.01) -> "ProbabilisticPlan":
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"p must be a probability, got {p}")
        with self._lock:
            self._p[site] = p
        return self

    def arm_stall(self, site: str, p: float, seconds: float
                  ) -> "ProbabilisticPlan":
        """With probability ``p`` per hit, sleep ``seconds`` at ``site``
        — degradation (delayed sequencing → delayed acks), not death."""
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"p must be a probability, got {p}")
        with self._lock:
            self._stall[site] = (p, seconds)
        return self

    def disarm(self, site: str) -> None:
        with self._lock:
            self._p.pop(site, None)
            self._stall.pop(site, None)

    def hit(self, site: str, **ctx) -> None:
        with self._lock:
            stall = self._stall.get(site)
            sleep_s = 0.0
            if stall is not None and self.rng.random() < stall[0]:
                self.stalls[site] = self.stalls.get(site, 0) + 1
                sleep_s = stall[1]
            p = self._p.get(site)
            fire = p is not None and self.rng.random() < p
            if fire:
                self.fires[site] = self.fires.get(site, 0) + 1
        if sleep_s:
            import time
            time.sleep(sleep_s)
        if fire:
            raise CrashInjected(site)


def arm(site: str, p: float = 0.01,
        rng: Optional[random.Random] = None) -> ProbabilisticPlan:
    """Probabilistically arm ``site``: installs a shared
    :class:`ProbabilisticPlan` (creating one if nothing is installed,
    reusing the installed one if it is probabilistic) and arms the site
    at rate ``p``. A later ``rng`` replaces the plan's rng so callers
    can re-seed between soak phases. Raises if a *different* kind of
    plan is installed — mixing one-shot budgets with probabilistic fire
    would make both unaccountable."""
    global _plan
    with _lock:
        plan = _plan
        if plan is None:
            plan = ProbabilisticPlan(rng=rng)
            _plan = plan
        elif not isinstance(plan, ProbabilisticPlan):
            raise RuntimeError("a non-probabilistic fault plan is installed")
        elif rng is not None:
            plan.rng = rng
    return plan.arm(site, p)


def disarm(site: str) -> None:
    """Remove one probabilistically armed site (no-op when the installed
    plan is not probabilistic or nothing is armed)."""
    plan = _plan
    if isinstance(plan, ProbabilisticPlan):
        plan.disarm(site)


# ------------------------------------------------- corruption injectors
# Seeded disk-rot simulators for the durability-integrity drills (ISSUE
# 10): they mutate a durable file IN PLACE the way real corruption does —
# a flipped bit, a truncation that may later regrow, a spliced-out record
# — and return an evidence dict so the drill can assert the detection
# layer reports the SAME location. They are deliberately plain file
# operations (no log/format knowledge): the integrity plane must detect
# arbitrary byte damage, not only damage shaped like its own framing.

CORRUPTION_KINDS = ("bitflip", "truncate", "splice")


def corrupt_bitflip(path: str, rng: random.Random) -> dict:
    """Flip ONE random bit somewhere in the file."""
    with open(path, "rb") as f:
        data = bytearray(f.read())
    if not data:
        return {"kind": "bitflip", "path": path, "skipped": "empty file"}
    off = rng.randrange(len(data))
    bit = rng.randrange(8)
    data[off] ^= 1 << bit
    with open(path, "wb") as f:
        f.write(data)
    return {"kind": "bitflip", "path": path, "offset": off, "bit": bit}


def corrupt_truncate(path: str, rng: random.Random) -> dict:
    """Cut the file at a random interior byte (NOT a record boundary on
    purpose — boundary truncation is the harder case the summary chain
    anchor exists for; callers wanting it can truncate exactly)."""
    import os
    size = os.path.getsize(path)
    if size < 2:
        return {"kind": "truncate", "path": path, "skipped": "too small"}
    cut = rng.randrange(1, size)
    with open(path, "r+b") as f:
        f.truncate(cut)
    return {"kind": "truncate", "path": path, "offset": cut,
            "dropped_bytes": size - cut}


def corrupt_splice(path: str, rng: random.Random) -> dict:
    """Remove one interior line (newline-framed files: a clean record
    splice) or, for binary files with too few lines, one interior 16-byte
    chunk — the 'a record vanished but the stream still looks healthy'
    case only a checksum CHAIN can see."""
    with open(path, "rb") as f:
        data = f.read()
    lines = data.split(b"\n")
    # newline-framed with at least 3 complete interior candidates
    if len(lines) >= 4 and data.endswith(b"\n"):
        i = rng.randrange(1, len(lines) - 2)  # never the first or torn slot
        cut = lines[:i] + lines[i + 1:]
        with open(path, "wb") as f:
            f.write(b"\n".join(cut))
        return {"kind": "splice", "path": path, "line": i,
                "dropped_bytes": len(lines[i]) + 1}
    if len(data) < 48:
        return {"kind": "splice", "path": path, "skipped": "too small"}
    off = rng.randrange(16, len(data) - 32)
    with open(path, "wb") as f:
        f.write(data[:off] + data[off + 16:])
    return {"kind": "splice", "path": path, "offset": off,
            "dropped_bytes": 16}


def corrupt_file(path: str, kind: str, rng: random.Random) -> dict:
    """Dispatch one corruption of ``kind`` ∈ :data:`CORRUPTION_KINDS`."""
    fn = {"bitflip": corrupt_bitflip, "truncate": corrupt_truncate,
          "splice": corrupt_splice}.get(kind)
    if fn is None:
        raise ValueError(f"unknown corruption kind {kind!r} "
                         f"(want one of {CORRUPTION_KINDS})")
    return fn(path, rng)


# Core sites declared centrally (hosts may declare more):
SITE_DELI_MID_WINDOW = declare_site("deli.sequence.mid_window")
SITE_OPLOG_MID_APPEND = declare_site("oplog.append.mid")
SITE_OPLOG_MID_SPILL = declare_site("oplog.spill.mid_line")
SITE_SUBMIT_POST_SEQUENCE = declare_site("serving.submit.post_sequence")
SITE_FLUSH_MID_BATCH = declare_site("serving.flush.mid_batch")
SITE_INGEST_MID_BATCH = declare_site("serving.ingest.mid_batch")
SITE_SUMMARIZER_POST_UPLOAD = declare_site("summarizer.post_upload")
SITE_CHECKPOINT_MID_WRITE = declare_site("checkpoint.mid_write")
SITE_APPLY_STALL = declare_site("serving.apply.stall")
