"""Shared retry backoff with decorrelated jitter.

Three call sites grew the same loop independently — the ingress bind
retry in ``server/ingress.py``, the columnar connect helper in
``server/columnar_ingress.py``, and now the resilient clients' reconnect
loops — each with its own base/cap/metric constants and its own flavor
of ``base * 2**attempt``. This module is the one implementation: a
:class:`Backoff` that yields *decorrelated jitter* delays (AWS
architecture-blog variant: ``sleep = min(cap, uniform(base, 3 * prev))``)
so a thundering herd of reconnecting clients spreads out instead of
retrying in lockstep, with a metrics hook so every consumer's retry
pressure is observable under its own counter name.

Deterministic under a seeded ``random.Random`` — the chaos soak arms
every client with its own seeded rng so reconnect schedules replay
exactly.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterator, Optional, TypeVar

T = TypeVar("T")


class Backoff:
    """Decorrelated-jitter delay source.

    ``base``    first/minimum delay (seconds)
    ``cap``     hard ceiling per delay (seconds)
    ``rng``     ``random.Random`` for jitter (shared module rng when None)
    ``metric``  counter name inc'd on every consumed delay (observability
                hook: bind retries, connect backoffs, session reconnects
                all count under their own name)
    ``registry``metrics registry exposing ``inc(name)``; resolved lazily
                to the global registry when None so importing this module
                never drags in telemetry
    """

    def __init__(self, base: float = 0.05, cap: float = 2.0,
                 rng: Optional[random.Random] = None,
                 metric: Optional[str] = None, registry=None):
        if base <= 0 or cap < base:
            raise ValueError(f"need 0 < base <= cap, got {base}, {cap}")
        self.base = base
        self.cap = cap
        self.rng = rng or random
        self.metric = metric
        self._registry = registry
        self._prev = base

    def reset(self) -> None:
        """Back to the first-attempt delay (call after a success so the
        next failure episode starts cheap)."""
        self._prev = self.base

    def next_delay(self) -> float:
        """The next sleep, decorrelated-jittered, counted if a metric
        name was bound."""
        delay = min(self.cap, self.rng.uniform(self.base, self._prev * 3))
        self._prev = max(self.base, delay)
        if self.metric:
            reg = self._registry
            if reg is None:
                from .telemetry import REGISTRY as reg
            reg.inc(self.metric)
        return delay

    def delays(self, attempts: int) -> Iterator[float]:
        """``attempts`` consecutive delays (a fresh episode)."""
        self.reset()
        for _ in range(max(0, attempts)):
            yield self.next_delay()


def retry(fn: Callable[[], T], attempts: int = 8,
          exceptions: tuple = (OSError,),
          backoff: Optional[Backoff] = None,
          sleep: Callable[[float], None] = time.sleep) -> T:
    """Call ``fn`` until it returns, sleeping a jittered delay between
    failures; the last exception propagates after ``attempts`` tries.
    ``sleep`` is injectable so tests (and async shims) control time."""
    bo = backoff or Backoff()
    bo.reset()
    last: Optional[BaseException] = None
    for i in range(max(1, attempts)):
        try:
            return fn()
        except exceptions as e:       # noqa: PERF203 — retry loop
            last = e
            if i + 1 < attempts:
                sleep(bo.next_delay())
    assert last is not None
    raise last
