"""Doc-axis sharding of the serving store: the product's multi-chip path.

Reference counterpart: Routerlicious scales by partitioning DOCUMENTS
across Kafka partitions and lambda instances (SURVEY.md §2.13/§2.14) —
documents are independent, so the TPU-native mapping is a 1-D ``docs``
mesh axis with every chip owning ``n_docs / n_chips`` rows of the
serving store's planes.

The merge kernel is per-doc math (vmap over docs, scan over ops, rolls
along the slot axis), so the sharded apply is expressed as a
``shard_map`` whose body is the SAME ``apply_string_batch`` /
``apply_string_batch_pallas`` the single-chip path runs — by
construction there is **zero cross-chip communication** on the apply
path (the dryrun asserts this from the compiled HLO). What does cross
chips: the host→device op buffer (5-8 B/op, broadcast), rare row
writes (overflow re-upload), and per-doc reads — all off the hot path.

``parallel/replicated.py`` layers the REPLICA axis (redundant copies +
digest agreement) on top; this module is the scale-out axis.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.merge_tree_kernel import (
    StringState, apply_string_batch, compact_string_state,
)
from ..ops.pallas_string_kernel import apply_string_batch_pallas
from .mesh import DOC_AXIS


def make_doc_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D ``docs`` mesh: each device owns a contiguous block of doc rows."""
    devices = jax.devices()
    n = n_devices or len(devices)
    return Mesh(np.array(devices[:n]), (DOC_AXIS,))


def doc_shard_count(mesh) -> int:
    """Doc-axis shard count of ``mesh`` (0 when it has no docs axis) —
    how many per-shard labeled collectors the health plane attaches."""
    try:
        return int(mesh.shape.get(DOC_AXIS, 0))
    except (AttributeError, TypeError):
        return 0


def shard_of_rows(rows, n_docs: int, n_shards: int):
    """Row → doc-shard index by contiguous block: the same row→device
    placement ``NamedSharding(P(DOC_AXIS, ...))`` produces, so the
    per-shard ``ops_applied`` rollups (ISSUE 4) credit the device that
    actually applied the op."""
    rows_per = max(1, n_docs // n_shards)
    return np.minimum(np.asarray(rows, np.int64) // rows_per,
                      n_shards - 1)


def doc_state_specs() -> StringState:
    """PartitionSpecs of every StringState plane on a docs-only mesh."""
    row = P(DOC_AXIS, None)
    return StringState(
        seq=row, client=row, removed_seq=row, removers=row, length=row,
        handle_op=row, handle_off=row, prop_val=P(DOC_AXIS, None, None),
        count=P(DOC_AXIS), overflow=P(DOC_AXIS),
    )


def shard_store_state(state: StringState, mesh: Mesh) -> StringState:
    """Place a store's planes onto the mesh, doc-row sharded."""
    if state.seq.shape[0] % mesh.devices.size != 0:
        raise ValueError(f"n_docs {state.seq.shape[0]} not divisible by "
                         f"mesh size {mesh.devices.size}")
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        state, doc_state_specs())


# jitted sharded programs, cached per (mesh, static flags) — the serving
# engine dispatches thousands of batches through the same few programs
_CACHE: dict = {}


def sharded_merge(mesh: Mesh, use_pallas: bool, tile: int, interpret: bool,
                  with_props: bool, fuse_compact: bool):
    """The sharded columnar/message merge: (state, 7×(D,O) planes[, min_seq])
    → state. Body = the single-chip kernel on each shard's doc block."""
    key = ("merge", mesh, use_pallas, tile, interpret, with_props,
           fuse_compact)
    if key not in _CACHE:
        specs = doc_state_specs()
        planes_spec = (P(DOC_AXIS, None),) * 7

        if fuse_compact:
            @functools.partial(jax.jit, donate_argnums=0)
            def fn(state, planes, ms):
                def body(state, planes, ms):
                    if use_pallas:
                        return apply_string_batch_pallas(
                            state, *planes, tile=tile, interpret=interpret,
                            min_seq=ms, with_props=with_props)
                    out = apply_string_batch(state, *planes,
                                             with_props=with_props)
                    return compact_string_state(out, ms, with_props)
                # check_vma=False: the Pallas body's output aval carries
                # no vma annotation (same setting as parallel/replicated.py)
                return jax.shard_map(
                    body, mesh=mesh,
                    in_specs=(specs, planes_spec, P(DOC_AXIS)),
                    out_specs=specs, check_vma=False)(state, planes, ms)
        else:
            @functools.partial(jax.jit, donate_argnums=0)
            def fn(state, planes):
                def body(state, planes):
                    if use_pallas:
                        return apply_string_batch_pallas(
                            state, *planes, tile=tile, interpret=interpret,
                            with_props=with_props)
                    return apply_string_batch(state, *planes,
                                              with_props=with_props)
                return jax.shard_map(
                    body, mesh=mesh, in_specs=(specs, planes_spec),
                    out_specs=specs, check_vma=False)(state, planes)
        _CACHE[key] = fn
    return _CACHE[key]


def sharded_compact(mesh: Mesh, with_props: bool):
    """Sharded zamboni: (state, (D,) min_seq) → state, per-shard compact."""
    key = ("compact", mesh, with_props)
    if key not in _CACHE:
        specs = doc_state_specs()

        @functools.partial(jax.jit, donate_argnums=0)
        def fn(state, ms):
            return jax.shard_map(
                lambda s, m: compact_string_state(s, m, with_props),
                mesh=mesh, in_specs=(specs, P(DOC_AXIS)),
                out_specs=specs, check_vma=False)(state, ms)
        _CACHE[key] = fn
    return _CACHE[key]


def map_state_specs():
    """PartitionSpecs of every MapState plane on a docs-only mesh."""
    from ..ops.map_kernel import MapState
    row = P(DOC_AXIS, None)
    return MapState(present=row, value=row, last_seq=row)


def shard_map_store_state(state, mesh: Mesh):
    """Place a map store's planes onto the mesh, doc-row sharded."""
    if state.present.shape[0] % mesh.devices.size != 0:
        raise ValueError(f"n_docs {state.present.shape[0]} not divisible "
                         f"by mesh size {mesh.devices.size}")
    return jax.tree.map(
        lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
        state, map_state_specs())


def sharded_map_merge(mesh: Mesh):
    """The doc-sharded columnar map apply (collective-free shard_map of
    the per-doc LWW reduction); one program per mesh — jit specializes
    on plane shapes."""
    key = ("map_merge", mesh)
    if key not in _CACHE:
        from ..ops.map_kernel import apply_map_batch
        specs = map_state_specs()

        @functools.partial(jax.jit, donate_argnums=0)
        def fn(state, planes):
            return jax.shard_map(
                apply_map_batch, mesh=mesh,
                in_specs=(specs,) + (P(DOC_AXIS, None),) * 4,
                out_specs=specs, check_vma=False)(state, *planes)
        _CACHE[key] = fn
    return _CACHE[key]


def tree_state_specs():
    """PartitionSpecs of every TreeState plane on a docs-only mesh."""
    from ..ops.tree_kernel import TreeState
    row = P(DOC_AXIS, None)
    return TreeState(node_id=row, parent=row, field=row, value=row,
                     type_=row, prev_sib=row, next_sib=row,
                     created_seq=row, overflow=P(DOC_AXIS))


def shard_tree_store_state(state, mesh: Mesh):
    """Place a tree store's planes onto the mesh, doc-row sharded."""
    if state.node_id.shape[0] % mesh.devices.size != 0:
        raise ValueError(f"n_docs {state.node_id.shape[0]} not divisible "
                         f"by mesh size {mesh.devices.size}")
    return jax.tree.map(
        lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
        state, tree_state_specs())


def sharded_tree_apply(mesh: Mesh):
    """The doc-sharded packed-plane tree apply: shard_map of the SAME
    single-chip record scan over each shard's doc block (tree merge is
    per-doc math — collective-free by construction)."""
    key = ("tree_apply", mesh)
    if key not in _CACHE:
        from ..ops.tree_kernel import apply_tree_planes
        specs = tree_state_specs()

        @functools.partial(jax.jit, donate_argnums=0)
        def fn(state, planes):
            return jax.shard_map(
                apply_tree_planes, mesh=mesh,
                in_specs=(specs, P(None, DOC_AXIS, None)),
                out_specs=specs, check_vma=False)(state, planes)
        _CACHE[key] = fn
    return _CACHE[key]


def axis_state_specs():
    """PartitionSpecs of the matrix AXIS store's StringState (2 axis rows
    per doc, adjacent, so doc-block sharding keeps a doc's row+col axes
    on one chip; shard blocks are even by construction)."""
    return doc_state_specs()


def shard_axis_store_state(state: StringState, mesh: Mesh) -> StringState:
    n_rows = state.seq.shape[0]
    if n_rows % (2 * mesh.devices.size) != 0:
        raise ValueError(f"axis rows {n_rows} not divisible by "
                         f"2×mesh size {2 * mesh.devices.size}")
    return shard_store_state(state, mesh)


def sharded_axis_apply(mesh: Mesh):
    """The doc-sharded axis scan (mutations + in-scan position
    resolves): shard_map of apply_axis_batch over each shard's axis-row
    block; resolve outputs come back row-sharded."""
    key = ("axis_apply", mesh)
    if key not in _CACHE:
        from ..ops.axis_kernel import apply_axis_batch
        specs = axis_state_specs()
        row = P(DOC_AXIS, None)

        @functools.partial(jax.jit, donate_argnums=0)
        def fn(state, planes):
            return jax.shard_map(
                apply_axis_batch, mesh=mesh,
                in_specs=(specs,) + (row,) * 7,
                out_specs=(specs, row, row), check_vma=False)(
                    state, *planes)
        _CACHE[key] = fn
    return _CACHE[key]


def sharded_cells_apply(mesh: Mesh, fww: bool):
    """The doc-sharded cell merge: each shard owns the cell POOL SLICE of
    its doc block (cells are doc-scoped, so routing by owning doc keeps
    the sort-merge shard-local — collective-free)."""
    key = ("cells_apply", mesh, fww)
    if key not in _CACHE:
        from ..ops.matrix_kernel import apply_cells_batch

        @functools.partial(jax.jit, donate_argnums=0)
        def fn(state, key_p, seq_p, val_p):
            def body(st, k, s, v):
                return jax.vmap(
                    functools.partial(apply_cells_batch, fww=fww))(
                        st, k, s, v)
            from ..ops.matrix_kernel import MatrixCellState
            specs = MatrixCellState(
                key=P(DOC_AXIS, None), seq=P(DOC_AXIS, None),
                value=P(DOC_AXIS, None), count=P(DOC_AXIS),
                overflow=P(DOC_AXIS))
            return jax.shard_map(
                body, mesh=mesh,
                in_specs=(specs, P(DOC_AXIS, None), P(DOC_AXIS, None),
                          P(DOC_AXIS, None)),
                out_specs=specs, check_vma=False)(
                    state, key_p, seq_p, val_p)
        _CACHE[key] = fn
    return _CACHE[key]


def assert_collective_free(mesh: Mesh, n_docs: int, capacity: int,
                           n_ops: int) -> str:
    """Compile the sharded merge at the given shape and prove the apply
    path needs NO cross-chip communication: the optimized HLO must contain
    zero collective ops. Returns the (empty) list rendered as evidence."""
    import jax.numpy as jnp
    state = shard_store_state(StringState.create(n_docs, capacity), mesh)
    planes = tuple(jnp.zeros((n_docs, n_ops), jnp.int32) for _ in range(7))
    ms = jnp.zeros((n_docs,), jnp.int32)
    fn = sharded_merge(mesh, use_pallas=False, tile=8, interpret=False,
                       with_props=False, fuse_compact=True)
    hlo = fn.lower(state, planes, ms).compile().as_text()
    bad = [op for op in ("all-reduce", "all-gather", "all-to-all",
                         "collective-permute", "reduce-scatter",
                         "collective-broadcast")
           if op in hlo]
    assert not bad, f"sharded merge HLO contains collectives: {bad}"
    return "collective-free"
