"""shard_map'd replicated merge step: the multi-chip op-apply pipeline.

The TPU-native shape of the reference's server pipeline (SURVEY.md §3.5):

- **doc axis sharded** over the ``docs`` mesh axis (Deli's Kafka partitioning
  of documents);
- **sequenced op batches broadcast** to every replica with an ICI
  ``all_gather`` over the ``replica`` axis (the Broadcaster → Redis → client
  fan-out);
- every replica applies the same ops to its copy of the doc-shard state, and
- a **cross-replica digest check** (``pmax``/``pmin`` over the replica axis)
  asserts bit-identical convergence — the race-detection analog of the
  reference's eventual-consistency fuzz asserts (SURVEY.md §5.2).

Each replica *ingests* a disjoint 1/R slice of each doc's op batch (its
"front door" share); the all-gather reassembles the full, seq-ordered batch
on every replica before applying.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.merge_tree_kernel import (
    StringState, apply_string_batch, string_state_digest,
)
from ..ops.pallas_string_kernel import apply_string_batch_pallas
from .mesh import DOC_AXIS, REPLICA_AXIS

# state planes: (D, S) sharded over docs, replicated over replica axis
STATE_SPEC = P(DOC_AXIS, None)
COUNT_SPEC = P(DOC_AXIS)
# op planes as ingested: (D, O) with the op axis split over replicas
OPS_INGEST_SPEC = P(DOC_AXIS, REPLICA_AXIS)


def _state_specs() -> StringState:
    return StringState(
        seq=STATE_SPEC, client=STATE_SPEC, removed_seq=STATE_SPEC,
        removers=STATE_SPEC, length=STATE_SPEC, handle_op=STATE_SPEC,
        handle_off=STATE_SPEC, prop_val=P(DOC_AXIS, None, None),
        count=COUNT_SPEC, overflow=COUNT_SPEC,
    )


def make_replicated_step(mesh, with_props: bool = True,
                         use_pallas: bool = False, pallas_tile: int = 8,
                         pallas_interpret: bool = False):
    """Build the jitted multi-chip step: (state, 7×(D,O) op planes) → (state,
    digests, replicas_agree). Op planes arrive sharded (docs, replica).

    ``use_pallas`` runs each shard's apply through the fused VMEM kernel
    (VERDICT r1 #1: the multi-chip path runs the production kernel) —
    annotate-free stores only; ``pallas_tile`` must divide the per-shard doc
    count. ``pallas_interpret`` exercises the same code path on the virtual
    CPU mesh."""

    # check_vma=False: after the all-gather the op batch is value-identical
    # across replicas but typed as replica-varying; the explicit pmax/pmin
    # digest agreement below is the (stronger, runtime) replication check.
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(_state_specs(),) + (OPS_INGEST_SPEC,) * 7,
        out_specs=(_state_specs(), COUNT_SPEC, P()),
        check_vma=False,
    )
    def step(state, kind, a0, a1, a2, seq, client, ref_seq):
        # Broadcaster: reassemble the full sequenced batch on every replica
        # via ICI all-gather over the replica axis (tiled on the op axis).
        gather = lambda x: jax.lax.all_gather(
            x, REPLICA_AXIS, axis=1, tiled=True)
        full = tuple(gather(x) for x in (kind, a0, a1, a2, seq, client,
                                         ref_seq))
        if use_pallas:
            new_state = apply_string_batch_pallas(
                state, *full, tile=pallas_tile,
                interpret=pallas_interpret, with_props=with_props)
        else:
            new_state = apply_string_batch(state, *full,
                                           with_props=with_props)
        digest = string_state_digest(new_state)
        # race detection: every replica must hold bit-identical state
        hi = jax.lax.pmax(digest, REPLICA_AXIS)
        lo = jax.lax.pmin(digest, REPLICA_AXIS)
        agree_local = jnp.all(hi == lo)
        agree = jax.lax.pmin(
            jax.lax.pmin(agree_local.astype(jnp.int32), REPLICA_AXIS),
            DOC_AXIS)
        return new_state, digest, agree

    return jax.jit(step, donate_argnums=0)


def shard_state(state: StringState, mesh) -> StringState:
    """Place host state onto the mesh with the step's shardings."""
    specs = _state_specs()
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        state, specs)


def shard_ops(mesh, *planes):
    sh = NamedSharding(mesh, OPS_INGEST_SPEC)
    return tuple(jax.device_put(jnp.asarray(p), sh) for p in planes)
