"""shard_map'd replicated merge step: the multi-chip op-apply pipeline.

The TPU-native shape of the reference's server pipeline (SURVEY.md §3.5):

- **doc axis sharded** over the ``docs`` mesh axis (Deli's Kafka partitioning
  of documents);
- **sequenced op batches broadcast** to every replica with an ICI
  ``all_gather`` over the ``replica`` axis (the Broadcaster → Redis → client
  fan-out);
- every replica applies the same ops to its copy of the doc-shard state, and
- a **cross-replica digest check** (``pmax``/``pmin`` over the replica axis)
  asserts bit-identical convergence — the race-detection analog of the
  reference's eventual-consistency fuzz asserts (SURVEY.md §5.2).

Each replica *ingests* a disjoint 1/R slice of each doc's op batch (its
"front door" share); the all-gather reassembles the full, seq-ordered batch
on every replica before applying.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.merge_tree_kernel import (
    StringState, apply_string_batch, string_state_digest,
)
from ..ops.pallas_string_kernel import apply_string_batch_pallas
from .mesh import DOC_AXIS, REPLICA_AXIS

# state planes: (D, S) sharded over docs, replicated over replica axis
STATE_SPEC = P(DOC_AXIS, None)
COUNT_SPEC = P(DOC_AXIS)
# op planes as ingested: (D, O) with the op axis split over replicas
OPS_INGEST_SPEC = P(DOC_AXIS, REPLICA_AXIS)


def _state_specs() -> StringState:
    return StringState(
        seq=STATE_SPEC, client=STATE_SPEC, removed_seq=STATE_SPEC,
        removers=STATE_SPEC, length=STATE_SPEC, handle_op=STATE_SPEC,
        handle_off=STATE_SPEC, prop_val=P(DOC_AXIS, None, None),
        count=COUNT_SPEC, overflow=COUNT_SPEC,
    )


def make_replicated_step(mesh, with_props: bool = True,
                         use_pallas: bool = False, pallas_tile: int = 8,
                         pallas_interpret: bool = False,
                         inject_divergence: bool = False):
    """Build the jitted multi-chip step: (state, 7×(D,O) op planes) → (state,
    digests, replicas_agree). Op planes arrive sharded (docs, replica).

    ``use_pallas`` runs each shard's apply through the fused VMEM kernel
    (VERDICT r1 #1: the multi-chip path runs the production kernel) —
    annotate-free stores only; ``pallas_tile`` must divide the per-shard doc
    count. ``pallas_interpret`` exercises the same code path on the virtual
    CPU mesh.

    ``inject_divergence`` is a chaos hook (faultpoint lineage, PR 1): it
    skews each replica's digest by its replica index BEFORE the pmax/pmin
    agreement check, so the on-device race detector itself has to notice —
    the health plane's divergence counter and SLO path get exercised by a
    real disagreement, not a mocked flag."""

    # check_vma=False: after the all-gather the op batch is value-identical
    # across replicas but typed as replica-varying; the explicit pmax/pmin
    # digest agreement below is the (stronger, runtime) replication check.
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(_state_specs(),) + (OPS_INGEST_SPEC,) * 7,
        out_specs=(_state_specs(), COUNT_SPEC, P()),
        check_vma=False,
    )
    def step(state, kind, a0, a1, a2, seq, client, ref_seq):
        # Broadcaster: reassemble the full sequenced batch on every replica
        # via ICI all-gather over the replica axis (tiled on the op axis).
        gather = lambda x: jax.lax.all_gather(
            x, REPLICA_AXIS, axis=1, tiled=True)
        full = tuple(gather(x) for x in (kind, a0, a1, a2, seq, client,
                                         ref_seq))
        if use_pallas:
            new_state = apply_string_batch_pallas(
                state, *full, tile=pallas_tile,
                interpret=pallas_interpret, with_props=with_props)
        else:
            new_state = apply_string_batch(state, *full,
                                           with_props=with_props)
        digest = string_state_digest(new_state)
        if inject_divergence:
            # chaos: make the replicas genuinely disagree so the check
            # below (and everything downstream of it) proves itself
            digest = digest + jax.lax.axis_index(REPLICA_AXIS).astype(
                digest.dtype)
        # race detection: every replica must hold bit-identical state
        hi = jax.lax.pmax(digest, REPLICA_AXIS)
        lo = jax.lax.pmin(digest, REPLICA_AXIS)
        agree_local = jnp.all(hi == lo)
        agree = jax.lax.pmin(
            jax.lax.pmin(agree_local.astype(jnp.int32), REPLICA_AXIS),
            DOC_AXIS)
        return new_state, digest, agree

    return jax.jit(step, donate_argnums=0)


def shard_state(state: StringState, mesh) -> StringState:
    """Place host state onto the mesh with the step's shardings."""
    specs = _state_specs()
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        state, specs)


def shard_ops(mesh, *planes):
    sh = NamedSharding(mesh, OPS_INGEST_SPEC)
    return tuple(jax.device_put(jnp.asarray(p), sh) for p in planes)


class OplogFollower:
    """Warm-standby replica trailing a leader engine through its durable
    oplog — the host-tier failover half of replication (the shard_map
    step above is the device tier).

    The follower owns a SECOND engine of the same family, anchored on a
    leader summary and sharing the leader's durable :class:`PartitionedLog`
    (the stand-in for both replicas consuming one Kafka topic).
    ``catch_up()`` reads each partition's new records past the follower's
    offsets, expands columnar batches, sorts by ``(doc, seq)`` (partition
    scan order is not chronological — same hazard ``_replay_tail``
    documents), and replays: sequencer state, resilience state (member
    set + dedup ledger), then the device apply queue. A per-doc
    applied-seq cursor makes replay idempotent, so racing the leader's
    appends is safe — a record seen twice is skipped by seq.

    ``promote()`` is the failover moment: one final catch-up (the leader
    is dead; the durable log is the complete record of everything it
    acked), then the follower's engine IS the leader — same digests as a
    never-failed run over the same ops, by the determinism invariant the
    chaos drills pin. Promotion counts ``failover_promotions_total`` and
    notes the flight recorder so a post-mortem shows when authority
    moved.
    """

    def __init__(self, leader, family: str = "string",
                 summary: Optional[dict] = None):
        from ..testing.chaos import engine_class
        self.family = family
        self.log = leader.log
        summary = summary if summary is not None else leader.summarize()
        self.engine = engine_class(family).load(summary, self.log)
        # everything up to the current sequencer state replayed at load;
        # new records land past these cursors
        self._offsets = [self.log.size(p)
                         for p in range(self.log.n_partitions)]
        self._applied: dict = {}
        for doc_id in list(self.engine._doc_rows):
            self._applied[doc_id] = self.engine.deli.doc_seq(doc_id)
        self.promoted = False
        self.caught_up_ops = 0

    def catch_up(self) -> int:
        """Drain the leader's log tail into the follower; returns the
        number of newly applied messages. Idempotent per (doc, seq)."""
        from ..core.protocol import MessageType
        tail = []
        for p in range(self.log.n_partitions):
            size = self.log.size(p)
            if size <= self._offsets[p]:
                continue
            for rec in self.log.read(p, from_offset=self._offsets[p],
                                     to_offset=size):
                tail.extend(rec.expand() if hasattr(rec, "expand")
                            else (rec,))
            self._offsets[p] = size
        tail.sort(key=lambda m: (m.doc_id, m.seq))
        eng = self.engine
        n = 0
        for msg in tail:
            if msg.seq <= self._applied.get(msg.doc_id, 0):
                continue    # raced an already-replayed record: skip
            eng.deli.replay(msg)
            eng._absorb_resilience(msg)
            if msg.type == MessageType.OP:
                eng._enqueue(msg.doc_id, msg)
                eng._min_seq[msg.doc_id] = max(
                    eng._min_seq.get(msg.doc_id, 0), msg.min_seq)
            self._applied[msg.doc_id] = msg.seq
            n += 1
        if n:
            eng._queue.sort(key=lambda dm: dm[1].seq)
            eng.flush()
        self.caught_up_ops += n
        return n

    def promote(self):
        """Fence the deposed leader, final catch-up from its durable log,
        then hand the engine over as the new authority.

        Order matters (ISSUE 10): the fence bump comes FIRST, so a
        not-actually-dead leader cannot land an append after the final
        catch-up read — anything it tries past this point raises
        ``FencedWriterError`` instead of silently extending a stream the
        follower already took over."""
        from ..utils import flight_recorder, telemetry
        new_epoch = self.engine.acquire_write_authority()
        n = self.catch_up()
        self.promoted = True
        telemetry.REGISTRY.inc("failover_promotions_total")
        flight_recorder.note("failover_promotion", family=self.family,
                             final_catchup_ops=n,
                             total_ops=self.caught_up_ops,
                             epoch=-1 if new_epoch is None else new_epoch)
        return self.engine


class ReplicaSetMetrics:
    """Health-plane rollup for a replicated mesh (ISSUE 4 piece 3).

    One labeled collector per replica rank attaches to the global
    registry (``ReplicaSet{replica=r}``), so the Prometheus exposition
    carries per-replica series instead of one anonymous blob. Digest
    agreement — the only race detector this stack has at scale — becomes
    a first-class signal: a disagreeing step increments
    ``replica_digest_divergence_total`` on the PROCESS registry (it is a
    property of the set, not a replica), warns through telemetry, and
    notes the flight recorder so a later crash dump carries the first
    divergence, not just the assertion that followed it.
    """

    def __init__(self, mesh, name: str = "ReplicaSet",
                 registry=None, logger=None):
        from ..utils import telemetry
        self.registry = registry if registry is not None \
            else telemetry.REGISTRY
        self.logger = logger if logger is not None \
            else telemetry.TelemetryLogger(namespace="replicaSet")
        self.n_replicas = int(mesh.shape.get(REPLICA_AXIS, 1))
        #: rank -> per-replica collector, attached with replica= labels
        self.per_replica = []
        for r in range(self.n_replicas):
            coll = telemetry.MetricsCollector()
            self.registry.attach(name, coll, labels={"replica": r})
            self.per_replica.append(coll)
        self.steps = 0
        self.divergences = 0

    def on_step(self, agree, n_ops: int) -> bool:
        """Account one replicated step: ``agree`` is the step's 0/1
        agreement scalar (device or host), ``n_ops`` the batch's op-slot
        count per replica. Returns the bool agreement."""
        ok = bool(agree)
        self.steps += 1
        for coll in self.per_replica:
            coll.inc("ops_applied", n_ops)
            coll.set_gauge("digest_agree", 1.0 if ok else 0.0)
        self.registry.set_gauge("digest_parity", 1.0 if ok else 0.0)
        if not ok:
            self.divergences += 1
            self.registry.inc("replica_digest_divergence_total")
            self.logger.send_warning(
                "replica_digest_divergence", step=self.steps,
                n_replicas=self.n_replicas)
            from ..utils import flight_recorder
            flight_recorder.note("replica_digest_divergence",
                                 step=self.steps,
                                 n_replicas=self.n_replicas)
        return ok
