"""Device mesh construction for the doc-sharded merge engine.

Reference counterpart: the scaling axis of Routerlicious — documents
partitioned across Kafka partitions (SURVEY.md §2.13/§2.14). Documents are
independent, so data parallelism over the doc axis is the native mapping;
a second ``replica`` axis replicates each doc shard for redundancy and read
scaling (the Broadcaster fan-out of §3.5 becomes an ICI all-gather of the
sequenced op batch across replicas).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

DOC_AXIS = "docs"
REPLICA_AXIS = "replica"


def make_mesh(n_devices: Optional[int] = None,
              replicas: Optional[int] = None) -> Mesh:
    """(replica, docs) mesh over the available devices.

    ``replicas`` defaults to 2 when the device count is even and > 1 (so the
    cross-replica digest check is meaningful), else 1.
    """
    devices = jax.devices()
    n = n_devices or len(devices)
    devices = devices[:n]
    if replicas is None:
        replicas = 2 if n % 2 == 0 and n > 1 else 1
    assert n % replicas == 0, (n, replicas)
    grid = np.array(devices).reshape(replicas, n // replicas)
    return Mesh(grid, (REPLICA_AXIS, DOC_AXIS))
