"""Device-mesh parallelism: doc-axis sharding + replica broadcast collectives.

Reference counterpart: document partitioning across Kafka partitions and the
Broadcaster fan-out (SURVEY.md §2.13–§2.14, §5.8), re-expressed as
``jax.sharding`` + ``shard_map`` with XLA collectives over ICI.
"""

from .mesh import make_mesh, DOC_AXIS, REPLICA_AXIS
from .replicated import (
    make_replicated_step, shard_state, shard_ops, STATE_SPEC, OPS_INGEST_SPEC,
)

__all__ = [
    "make_mesh", "DOC_AXIS", "REPLICA_AXIS", "make_replicated_step",
    "shard_state", "shard_ops", "STATE_SPEC", "OPS_INGEST_SPEC",
]
