"""DeltaManager: the client-side op pump and connection state machine.

Reference counterpart: ``DeltaManager`` + ``ConnectionManager`` in
``@fluidframework/container-loader`` (SURVEY.md §2.10, §3.1–3.3):

- **inbound**: sequenced ops from the live stream and from catch-up tail
  reads merge into one strictly-ordered queue (``DeltaQueue``); duplicates
  dropped, gaps back-filled from delta storage;
- **outbound**: local ops are stamped with the current reference sequence
  number and submitted on the active connection;
- **connection state machine**: disconnected → connecting → catching_up →
  connected, with auto-reconnect (exponential backoff expressed as an
  attempt counter — the host loop owns real timers), readonly fallback, and
  nack-triggered reconnection;
- the sequenced echo of the client's own op is the *ack* (§1 data flow).
"""

from __future__ import annotations

import enum
from typing import Any, Callable, Dict, List, Optional

from ..core.protocol import MessageType, SequencedDocumentMessage
from ..drivers.definitions import DocumentService
from .delta_queue import DeltaQueue


class ConnectionState(enum.Enum):
    DISCONNECTED = "disconnected"
    CONNECTING = "connecting"
    CATCHING_UP = "catching_up"
    CONNECTED = "connected"


class DeltaManager:
    def __init__(self, service: DocumentService,
                 auto_reconnect: bool = True):
        self.service = service
        self.auto_reconnect = auto_reconnect
        self.state = ConnectionState.DISCONNECTED
        self.readonly = False
        self.connection = None
        self.client_id: Optional[int] = None
        self.reconnect_attempts = 0
        self._handler: Optional[Callable[[SequencedDocumentMessage], None]] = None
        self._inbound: Optional[DeltaQueue] = None
        self._listeners: Dict[str, List[Callable]] = {}

    # -------------------------------------------------------------- listeners

    def on(self, event: str, fn: Callable) -> None:
        self._listeners.setdefault(event, []).append(fn)

    def _emit(self, event: str, *args) -> None:
        for fn in self._listeners.get(event, []):
            fn(*args)

    # ------------------------------------------------------------- properties

    @property
    def last_sequence_number(self) -> int:
        return self._inbound.last_seq if self._inbound is not None else 0

    @property
    def connected(self) -> bool:
        return self.state == ConnectionState.CONNECTED

    # ----------------------------------------------------------------- wiring

    def attach_op_handler(self, handler: Callable[[SequencedDocumentMessage], None],
                          last_seq: int = 0) -> None:
        """Install the inbound handler starting after ``last_seq`` (the
        summary's sequence number on load) — reference:
        DeltaManager.attachOpHandler (§3.1)."""
        self._handler = handler
        self._inbound = DeltaQueue(handler, lambda m: m.seq,
                                   initial_seq=last_seq)

    @property
    def inbound(self) -> DeltaQueue:
        assert self._inbound is not None, "attach_op_handler first"
        return self._inbound

    # ------------------------------------------------------------- connection

    def connect(self) -> None:
        assert self._inbound is not None, "attach_op_handler before connect"
        if self.state != ConnectionState.DISCONNECTED:
            return
        self.state = ConnectionState.CONNECTING
        try:
            conn = self.service.connect_to_delta_stream()
        except Exception:
            self.state = ConnectionState.DISCONNECTED
            self.reconnect_attempts += 1
            raise
        self.connection = conn
        self.client_id = conn.client_id
        self.state = ConnectionState.CATCHING_UP
        # live ops stream straight into the ordered inbound queue; the tail
        # read below fills anything we missed while disconnected — DeltaQueue
        # drops the overlap and orders the rest
        conn.on_op(self._inbound.push)
        conn.on_nack(self._on_nack)
        conn.on_signal(lambda sig: self._emit("signal", sig))
        self.catch_up()
        self.state = ConnectionState.CONNECTED
        self.reconnect_attempts = 0
        self._emit("connected", self.client_id)

    def catch_up(self) -> None:
        """Back-fill the gap between last processed seq and the live stream
        via delta storage (reference: fetch op tail, §3.1)."""
        q = self._inbound
        for msg in self.service.delta_storage.get_deltas(q.last_seq):
            q.push(msg)
        # a gap can remain only if the storage read raced new live ops that
        # themselves raced ahead; re-read until the queue is gap-free
        while q.has_gap() is not None:
            before = q.last_seq
            for msg in self.service.delta_storage.get_deltas(q.last_seq):
                q.push(msg)
            if q.last_seq == before:
                break  # nothing new: the gap is in flight, live push fills it

    def disconnect(self, reason: str = "") -> None:
        if self.connection is not None:
            conn, self.connection = self.connection, None
            try:
                conn.disconnect()
            finally:
                self.client_id = None
        if self.state != ConnectionState.DISCONNECTED:
            self.state = ConnectionState.DISCONNECTED
            self._emit("disconnected", reason)

    def reconnect(self, reason: str = "") -> None:
        """Drop the current connection and establish a new one (new client
        id, fresh client-seq space — pending-op resubmit is the runtime's
        job via the 'connected' event)."""
        self.disconnect(reason)
        self.reconnect_attempts += 1
        if self.auto_reconnect and not self.readonly:
            self.connect()

    def set_readonly(self, readonly: bool) -> None:
        self.readonly = readonly
        self._emit("readonly", readonly)

    def _on_nack(self, nack: Any) -> None:
        self._emit("nack", nack)
        # reference behavior: a nack forces reconnection; pending ops are
        # resubmitted (and rebased) by the runtime on the new connection
        if self.auto_reconnect:
            self.reconnect(f"nack:{getattr(nack, 'reason', nack)}")

    # --------------------------------------------------------------- outbound

    def submit(self, contents: Any, type: MessageType = MessageType.OP,
               address: Optional[str] = None) -> int:
        """Submit one op stamped with the current reference sequence number;
        returns its client sequence number."""
        assert not self.readonly, "submit on readonly container"
        assert self.connection is not None and self.connected, \
            "submit while disconnected (runtime should queue + resubmit)"
        return self.connection.submit(
            contents, type, ref_seq=self.last_sequence_number,
            address=address)

    def submit_signal(self, contents: Any) -> None:
        """Ephemeral broadcast (reference: submitSignal) — fire-and-forget,
        silently dropped while disconnected (signals are best-effort)."""
        if self.connection is not None and self.connected:
            self.connection.submit_signal(contents)

    def submit_noop(self) -> None:
        """Heartbeat: advances this client's refSeq (and thus the MSN)
        without consuming a client sequence number."""
        if self.connection is not None and self.connected:
            self.connection.submit(None, MessageType.NOOP,
                                   ref_seq=self.last_sequence_number)
