"""Loader (L2): container lifecycle, delta manager, protocol/quorum.

Reference counterpart: ``@fluidframework/container-loader`` — SURVEY.md §2.10.
"""

from .container import Container, ContainerState, Loader
from .delta_manager import ConnectionState, DeltaManager
from .delta_queue import DeltaQueue
from .protocol import ProtocolHandler, Quorum, QuorumProposal

__all__ = [
    "Container",
    "ContainerState",
    "Loader",
    "ConnectionState",
    "DeltaManager",
    "DeltaQueue",
    "ProtocolHandler",
    "Quorum",
    "QuorumProposal",
]
