"""Container: document lifecycle — load, catch up, connect, process, close.

Reference counterpart: ``Loader`` / ``Container`` in
``@fluidframework/container-loader`` (SURVEY.md §2.10, §3.1): resolve a
document service, load the latest summary, initialize the protocol handler
(quorum + seq/minSeq from attributes), instantiate the runtime from the
summary, replay the op tail through the same path as live ops, then connect.

The runtime side is pluggable (reference: the code proposal / runtime
factory): ``runtime_factory(container, runtime_summary) -> runtime`` where
runtime exposes ``process(msg, local)`` and optionally
``set_connection_state(connected, client_id)`` and ``summarize()``.
"""

from __future__ import annotations

import enum
from typing import Any, Callable, Dict, List, Optional

from ..core.protocol import MessageType, SequencedDocumentMessage
from ..drivers.definitions import DocumentService, DocumentServiceFactory
from ..utils import tracing
from .delta_manager import DeltaManager
from .protocol import ProtocolHandler

RuntimeFactory = Callable[["Container", Optional[dict]], Any]

# message types routed to the runtime (everything passes the protocol
# handler first — SURVEY.md §3.2)
_RUNTIME_TYPES = (MessageType.OP, MessageType.SUMMARIZE,
                  MessageType.SUMMARY_ACK, MessageType.SUMMARY_NACK)


class ContainerState(enum.Enum):
    LOADING = "loading"
    LOADED = "loaded"
    CLOSED = "closed"


class Container:
    def __init__(self, service: DocumentService,
                 runtime_factory: RuntimeFactory):
        self.service = service
        self.state = ContainerState.LOADING
        self.protocol = ProtocolHandler()
        self.delta_manager = DeltaManager(service)
        self.base_seq = 0          # seq of the summary this container loaded
        self.runtime: Any = None
        self._runtime_factory = runtime_factory
        self._listeners: Dict[str, List[Callable]] = {}
        # every client id this container has held across reconnects (the
        # "is this op mine" set — see _process)
        self._my_client_ids: set = set()

    # -------------------------------------------------------------- listeners

    def on(self, event: str, fn: Callable) -> None:
        self._listeners.setdefault(event, []).append(fn)

    def _emit(self, event: str, *args) -> None:
        for fn in self._listeners.get(event, []):
            fn(*args)

    # ------------------------------------------------------------------- load

    @classmethod
    def load(cls, service: DocumentService,
             runtime_factory: RuntimeFactory,
             connect: bool = True) -> "Container":
        """Load from the latest summary + op tail (SURVEY.md §3.1)."""
        c = cls(service, runtime_factory)
        runtime_summary: Optional[dict] = None
        latest = service.summary_storage.get_latest_summary()
        if latest is not None:
            summary, seq = latest
            c.protocol = ProtocolHandler.load(summary.get("protocol") or {})
            runtime_summary = summary.get("runtime")
            c.base_seq = c.protocol.seq
            if c.base_seq != seq:
                # a summary whose protocol attributes disagree with its
                # handle seq cannot be resumed from — replaying the tail
                # against it would double-apply ops
                raise ValueError(
                    f"summary seq mismatch: protocol attributes say "
                    f"{c.base_seq}, summary handle says {seq}")
        c.delta_manager.attach_op_handler(c._process, last_seq=c.base_seq)
        c.runtime = runtime_factory(c, runtime_summary)
        c.delta_manager.on("connected", c._on_connected)
        c.delta_manager.on("disconnected", c._on_disconnected)
        c.delta_manager.on("signal", lambda sig: c._emit("signal", sig))
        c.state = ContainerState.LOADED
        if connect:
            c.connect()
        else:
            # offline catch-up: replay whatever the op store already has
            c.delta_manager.catch_up()
        return c

    # ------------------------------------------------------------- connection

    def connect(self) -> None:
        assert self.state == ContainerState.LOADED, "connect on closed container"
        self.delta_manager.connect()

    def disconnect(self, reason: str = "") -> None:
        self.delta_manager.disconnect(reason)

    @property
    def connected(self) -> bool:
        return self.delta_manager.connected

    @property
    def client_id(self) -> Optional[int]:
        return self.delta_manager.client_id

    @property
    def quorum(self):
        return self.protocol.quorum

    def _on_connected(self, client_id: int) -> None:
        self._my_client_ids.add(client_id)
        if self.runtime is not None and \
                hasattr(self.runtime, "set_connection_state"):
            self.runtime.set_connection_state(True, client_id)
        self._emit("connected", client_id)

    def _on_disconnected(self, reason: str) -> None:
        if self.runtime is not None and \
                hasattr(self.runtime, "set_connection_state"):
            self.runtime.set_connection_state(False, None)
        self._emit("disconnected", reason)

    # ---------------------------------------------------------------- inbound

    def _process(self, msg: SequencedDocumentMessage) -> None:
        self.protocol.process(msg)
        if msg.type in _RUNTIME_TYPES and self.runtime is not None:
            # "local" = submitted by THIS container on ANY of its
            # connections: after a reconnect, catch-up echoes of ops
            # submitted under the PREVIOUS client id must still ack the
            # pending records — judging by the current id alone would
            # resubmit already-sequenced ops and duplicate them for every
            # client (found by the network-driver e2e drill; the local
            # driver's synchronous acks never expose the race)
            local = msg.client_id in self._my_client_ids
            if local:
                # the batch's span tree closes here: the submitting
                # client processing its own sequenced echo IS the ack
                with tracing.span("ack", parent=msg.trace, seq=msg.seq):
                    self.runtime.process(msg, local)
            else:
                self.runtime.process(msg, local)
        self._emit("op", msg)

    # --------------------------------------------------------------- outbound

    def submit(self, contents: Any, type: MessageType = MessageType.OP,
               address: Optional[str] = None) -> int:
        """Runtime-facing submit (reference: ContainerContext.submitFn)."""
        return self.delta_manager.submit(contents, type, address)

    def submit_signal(self, contents: Any) -> None:
        """Ephemeral broadcast to currently-connected clients (reference:
        IContainer.submitSignal; listen via ``on("signal", fn)``)."""
        self.delta_manager.submit_signal(contents)

    def propose(self, key: str, value: Any) -> None:
        """Quorum proposal (accepted once MSN passes its seq)."""
        self.delta_manager.submit({"key": key, "value": value},
                                  MessageType.PROPOSAL)

    # ------------------------------------------------------------------ close

    def close(self) -> None:
        if self.state != ContainerState.CLOSED:
            self.disconnect("close")
            self.state = ContainerState.CLOSED
            self._emit("closed")


class Loader:
    """Resolve document ids to loaded containers (reference: Loader.resolve).

    The code-loader mapping of the reference (quorum code proposal →
    runtime factory) is collapsed to a single factory per Loader; the quorum
    proposal mechanism itself lives in ``protocol.Quorum``.
    """

    def __init__(self, factory: DocumentServiceFactory,
                 runtime_factory: RuntimeFactory):
        self.factory = factory
        self.runtime_factory = runtime_factory

    def resolve(self, doc_id: str, connect: bool = True) -> Container:
        service = self.factory.create_document_service(doc_id)
        return Container.load(service, self.runtime_factory, connect=connect)
