"""DeltaQueue: the ordered op pump with pause/resume and continuity checks.

Reference counterpart: ``DeltaQueue`` inside
``@fluidframework/container-loader`` (SURVEY.md §2.10, §3.2): inbound ops are
delivered strictly in sequence-number order; duplicates (overlap between the
catch-up tail read and the live stream) are dropped; out-of-order arrivals
are buffered until the gap fills; the queue can be paused (during catch-up or
summarizer load) and resumed without losing ordering.
"""

from __future__ import annotations

import heapq
from typing import Callable, Generic, List, Optional, TypeVar

T = TypeVar("T")


class DeltaQueue(Generic[T]):
    def __init__(self, handler: Callable[[T], None],
                 seq_of: Callable[[T], int], initial_seq: int = 0):
        self._handler = handler
        self._seq_of = seq_of
        self.last_seq = initial_seq
        self._heap: List[tuple] = []   # (seq, tiebreak, item)
        self._tiebreak = 0
        self._paused = 0
        self._draining = False
        self.dropped_duplicates = 0

    # ------------------------------------------------------------ flow control

    def pause(self) -> None:
        self._paused += 1

    def resume(self) -> None:
        assert self._paused > 0, "resume without matching pause"
        self._paused -= 1
        if self._paused == 0:
            self._drain()

    @property
    def paused(self) -> bool:
        return self._paused > 0

    @property
    def pending(self) -> int:
        return len(self._heap)

    # ----------------------------------------------------------------- intake

    def push(self, item: T) -> None:
        seq = self._seq_of(item)
        if seq <= self.last_seq:
            # tail-read / live-stream overlap: already processed
            self.dropped_duplicates += 1
            return
        self._tiebreak += 1
        heapq.heappush(self._heap, (seq, self._tiebreak, item))
        self._drain()

    def _drain(self) -> None:
        if self._paused or self._draining:
            return
        # re-entrancy guard: a handler may push (the local pipeline is
        # synchronous) — the outer drain loop picks those up
        self._draining = True
        try:
            while self._heap and not self._paused:
                seq = self._heap[0][0]
                if seq <= self.last_seq:
                    heapq.heappop(self._heap)
                    self.dropped_duplicates += 1
                    continue
                if seq != self.last_seq + 1:
                    break  # gap: wait for the tail fetch to fill it
                _, _, item = heapq.heappop(self._heap)
                self.last_seq = seq
                self._handler(item)
        finally:
            self._draining = False

    def has_gap(self) -> Optional[int]:
        """If blocked on a gap, the first missing seq; else None."""
        if self._heap and self._heap[0][0] > self.last_seq + 1:
            return self.last_seq + 1
        return None
