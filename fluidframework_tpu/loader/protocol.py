"""Protocol handler: quorum membership and proposals.

Reference counterpart: the protocol handler + ``Quorum`` in
``@fluidframework/container-loader`` (SURVEY.md §2.10, §3.1): tracks connected
clients (join/leave ops), document-level proposals (e.g. the code proposal),
and the (seq, minSeq) protocol state every summary captures. A proposal is
*accepted* once the MSN passes its sequence number — i.e. every connected
client has seen it (reference: Quorum approval rule).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core.protocol import MessageType, SequencedDocumentMessage


@dataclasses.dataclass
class QuorumProposal:
    key: str
    value: Any
    seq: int                 # sequence number of the proposal op
    client_id: int
    accepted: bool = False


class Quorum:
    """Connected-client set + accepted document configuration."""

    def __init__(self):
        self.members: Dict[int, dict] = {}
        self._pending: List[QuorumProposal] = []
        self._accepted: Dict[str, QuorumProposal] = {}
        self._listeners: Dict[str, List[Callable]] = {}

    # -------------------------------------------------------------- listeners

    def on(self, event: str, fn: Callable) -> None:
        self._listeners.setdefault(event, []).append(fn)

    def _emit(self, event: str, *args) -> None:
        for fn in self._listeners.get(event, []):
            fn(*args)

    # ---------------------------------------------------------------- queries

    def get(self, key: str, default: Any = None) -> Any:
        p = self._accepted.get(key)
        return p.value if p is not None else default

    def has(self, key: str) -> bool:
        return key in self._accepted

    @property
    def pending(self) -> List[QuorumProposal]:
        return list(self._pending)

    # ------------------------------------------------------------- op intake

    def add_member(self, client_id: int, details: Optional[dict] = None) -> None:
        self.members[client_id] = details or {}
        self._emit("addMember", client_id)

    def remove_member(self, client_id: int) -> None:
        if client_id in self.members:
            del self.members[client_id]
            self._emit("removeMember", client_id)

    def add_proposal(self, key: str, value: Any, seq: int,
                     client_id: int) -> None:
        self._pending.append(QuorumProposal(key, value, seq, client_id))

    def advance_min_seq(self, min_seq: int) -> None:
        """Accept every pending proposal whose seq the MSN has passed."""
        still: List[QuorumProposal] = []
        for p in self._pending:
            if p.seq <= min_seq:
                p.accepted = True
                self._accepted[p.key] = p
                self._emit("approveProposal", p)
            else:
                still.append(p)
        self._pending = still

    # ------------------------------------------------------------- snapshots

    def snapshot(self) -> dict:
        return {
            "members": {str(cid): d for cid, d in self.members.items()},
            "accepted": {k: {"value": p.value, "seq": p.seq,
                             "clientId": p.client_id}
                         for k, p in self._accepted.items()},
            "pending": [{"key": p.key, "value": p.value, "seq": p.seq,
                         "clientId": p.client_id} for p in self._pending],
        }

    @classmethod
    def load(cls, snap: dict) -> "Quorum":
        q = cls()
        for cid, d in snap.get("members", {}).items():
            q.members[int(cid)] = d
        for k, pd in snap.get("accepted", {}).items():
            p = QuorumProposal(k, pd["value"], pd["seq"], pd["clientId"],
                               accepted=True)
            q._accepted[k] = p
        for pd in snap.get("pending", []):
            q._pending.append(QuorumProposal(
                pd["key"], pd["value"], pd["seq"], pd["clientId"]))
        return q


class ProtocolHandler:
    """Document-level protocol state: seq / minSeq counters + quorum.

    Every inbound sequenced message passes through here before the runtime
    (SURVEY.md §3.2: Container.processRemoteMessage → ProtocolHandler).
    """

    def __init__(self, quorum: Optional[Quorum] = None,
                 seq: int = 0, min_seq: int = 0):
        self.quorum = quorum if quorum is not None else Quorum()
        self.seq = seq
        self.min_seq = min_seq

    def process(self, msg: SequencedDocumentMessage) -> None:
        assert msg.seq == self.seq + 1, \
            f"protocol seq gap: have {self.seq}, got {msg.seq}"
        self.seq = msg.seq
        if msg.type == MessageType.CLIENT_JOIN:
            self.quorum.add_member(msg.contents["clientId"],
                                   (msg.contents or {}).get("details"))
        elif msg.type == MessageType.CLIENT_LEAVE:
            self.quorum.remove_member(msg.contents["clientId"])
        elif msg.type == MessageType.PROPOSAL:
            self.quorum.add_proposal(
                msg.contents["key"], msg.contents["value"], msg.seq,
                msg.client_id)
        if msg.min_seq > self.min_seq:
            self.min_seq = msg.min_seq
            self.quorum.advance_min_seq(self.min_seq)

    # -------------------------------------------------------------- snapshots

    def attributes(self) -> dict:
        """The protocol attributes blob every summary carries
        (reference: .protocol/attributes in the summary tree)."""
        return {"sequenceNumber": self.seq,
                "minimumSequenceNumber": self.min_seq}

    def snapshot(self) -> dict:
        return {"attributes": self.attributes(),
                "quorum": self.quorum.snapshot()}

    @classmethod
    def load(cls, snap: dict) -> "ProtocolHandler":
        attrs = snap.get("attributes", {})
        return cls(quorum=Quorum.load(snap.get("quorum", {})),
                   seq=attrs.get("sequenceNumber", 0),
                   min_seq=attrs.get("minimumSequenceNumber", 0))
