"""Tensor op schema and batched device kernels (the op-merge engine).

This is the TPU-native replacement for the reference's hot path
(``ContainerRuntime.process`` → ``SharedObject.process`` → ``MergeTree``
insert/remove — SURVEY.md §3.2): instead of an object-graph walk per op, ops are
fixed-width packed records in a (doc × op) batch and one jit'd step applies them
for thousands of documents at once, with the op axis a ``lax.scan`` (total order
within a doc is a hard data dependency) and the doc axis vmapped/sharded.
"""

from .schema import OpKind, OpBatch, SEGMENT_FIELDS

__all__ = ["OpKind", "OpBatch", "SEGMENT_FIELDS"]
