"""Batched MergeTree op-apply kernel — the north-star hot path on device.

Reference counterpart: ``@fluidframework/merge-tree`` ``MergeTree.
insertSegments`` / ``markRangeRemoved`` and the container-runtime ``processOp``
loop above them (SURVEY.md §2.1, §3.2). The reference walks a B-tree object
graph per op; here the *entire* merge — position resolution in the op's
(refSeq, client) perspective, concurrent-insert tie-break, segment splits,
tombstoning with overlapping removes — is (doc × op × segment) tensor math:
one ``lax.scan`` over the op axis (total order per doc is a hard data
dependency) with every document in the batch advanced in parallel per step.

Design invariants that make this tractable on a TPU:

- **Acked-only state.** The device holds sequenced state only; optimistic
  local ops, acks, and rebase live in the host client (``models``). With no
  pending segments, the reference's tie-break ("new segment goes after
  pending-local segments, before lower-seq acked ones") collapses to: *insert
  at the leftmost slot whose perspective-prefix equals the position* — every
  acked segment has seq < the incoming op's seq. Later-sequenced concurrent
  inserts therefore land left of earlier ones, exactly like the oracle.
- **Position-ordered dense slots.** Active segments occupy slots 0..n-1 in
  document order. An insert or split always shifts the tail of the slot
  arrays right by 1 or 2, so every plane update is a ``roll`` plus masked
  selects — pure vector passes, **no general gather/scatter** (dynamic
  gathers lower to scalar loops on TPU and measure ~1000× slower here).
  Scalar extractions (the containing slot's prefix) use one-hot masked
  reductions for the same reason; compaction sorts all planes together
  with a multi-operand ``lax.sort`` instead of argsort + gather.
- **Client indexes + remover bitmask.** Clients of a doc are interned to
  indexes 0..31 by the host; "removed by client c" (needed for perspectives
  whose refSeq predates the client's own removal) is one bit in an int32
  plane, supporting the reference's overlapping-remove client list.
- **Payload handles.** Text bytes never reach the device: segments carry
  (handle_op, handle_off, len); splits just offset the handle, and the host
  text table materializes strings on read. Markers are length-1 runs with a
  marker-table handle.

Capacity: S slots per doc. An op that would overflow S sets a sticky per-doc
overflow flag and leaves the doc unchanged; the host drains such docs through
the oracle and re-uploads after compaction (the gap-buffer escape hatch of
SURVEY.md §7 risk (b)).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.constants import NOT_REMOVED
from .schema import OpKind

MAX_CLIENTS = 32  # remover bitmask width (int32)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class StringState:
    """Device-resident acked merge-tree state for D docs × S segment slots."""

    seq: jax.Array          # (D, S) int32 insert seq
    client: jax.Array       # (D, S) int32 inserting client index
    removed_seq: jax.Array  # (D, S) int32, NOT_REMOVED if live
    removers: jax.Array     # (D, S) int32 bitmask of removing client indexes
    length: jax.Array       # (D, S) int32 run length
    handle_op: jax.Array    # (D, S) int32 payload table id
    handle_off: jax.Array   # (D, S) int32 offset within the payload
    prop_val: jax.Array     # (D, S, K) int32 value handle per property key
    count: jax.Array        # (D,)  int32 active slot count
    overflow: jax.Array     # (D,)  int32 sticky overflow flag

    @staticmethod
    def create(n_docs: int, capacity: int, n_props: int = 4) -> "StringState":
        """n_props: K property-key planes for annotate (per-key LWW). Keys
        are host-interned to plane indexes; a store needing more distinct
        keys than K must be created wider (static shape)."""
        z = lambda fill=0: jnp.full((n_docs, capacity), fill, dtype=jnp.int32)
        return StringState(
            seq=z(), client=z(), removed_seq=z(NOT_REMOVED), removers=z(),
            length=z(), handle_op=z(), handle_off=z(),
            prop_val=jnp.zeros((n_docs, capacity, n_props), jnp.int32),
            count=jnp.zeros((n_docs,), jnp.int32),
            overflow=jnp.zeros((n_docs,), jnp.int32),
        )


# ----------------------------------------------------------- single-doc math
# All helpers below operate on ONE document (S-vectors) and are vmapped over
# the doc axis by the batch step.

def _iota(n):
    """(n,) int32 index vector built from a 2-D iota: usable both as a plain
    XLA constant and inside Pallas kernels (Mosaic rejects 1-D iota, and
    pallas_call rejects captured trace-time constants like jnp.arange)."""
    return jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)[0]


def _active(s, S):
    return _iota(S) < s["count"]


def _prop_keys(s):
    """The per-key property planes of a state dict, in key-plane order.

    Properties live as K separate 2-D (S,) / (D, S) planes named
    ``prop0..propK-1`` — NOT one (S, K) array: a tiny minor dim gets
    lane-padded to 128 in TPU vector layouts, which both bloats VMEM ~32×
    and blocks Mosaic's i1 reshapes. The XLA entry points split/restack
    the state's (D, S, K) ``prop_val`` at the boundary."""
    return tuple(f"prop{i}" for i in range(len(s))
                 if f"prop{i}" in s)


def _visible(s, ref_seq, client_idx):
    S = s["seq"].shape[0]
    ins = (s["seq"] <= ref_seq) | (s["client"] == client_idx)
    rem = (s["removed_seq"] <= ref_seq) | \
          (((s["removers"] >> jnp.clip(client_idx, 0, MAX_CLIENTS - 1)) & 1)
           .astype(bool) & (client_idx >= 0))
    return _active(s, S) & ins & ~rem


def _cumsum(x):
    """Hillis-Steele inclusive prefix sum along the last axis via static
    shifts. Equivalent to ``jnp.cumsum`` but built from roll/where/add so it
    also lowers inside Pallas kernels (Mosaic has no cumsum primitive)."""
    S = x.shape[-1]
    idx = _iota(S)
    step = 1
    while step < S:
        x = x + jnp.where(idx >= step, jnp.roll(x, step, axis=-1), 0)
        step *= 2
    return x


def _prefix(s, vis):
    pl = jnp.where(vis, s["length"], 0)
    cum = _cumsum(pl)
    return cum - pl, cum - pl + pl  # (exclusive prefix, inclusive end)


_PLANES = ("seq", "client", "removed_seq", "removers", "length",
           "handle_op", "handle_off")


def _insert_one(s, pos, length, handle, seq, client_idx, ref_seq,
                with_props=True):
    """Apply one insert to one doc (S-vector planes in dict s).

    Gather-free: the result is ``s`` below the cut slot, ``roll(s, 1)``
    (boundary insert) or ``roll(s, 2)`` (split) above it, with the new
    segment written at the cut and the split's right piece fixed up in
    place — ``roll(s, 2)`` already carries the containing slot's values
    to the right-piece position. Wrapped roll values only ever land on
    slots that are overwritten or beyond ``count``.
    """
    S = s["seq"].shape[0]
    i = _iota(S)
    vis = _visible(s, ref_seq, client_idx)
    pre, end = _prefix(s, vis)

    inside = vis & (pre < pos) & (pos < end)
    has_inside = jnp.any(inside)
    # first-true index (min over masked iota): Mosaic lowers min-reductions
    # but not argmax; S when absent, and every use is has_inside-guarded
    j = jnp.min(jnp.where(inside, i, S))        # containing slot (split case)
    off = pos - jnp.sum(jnp.where(inside, pre, 0))   # pre[j], one-hot sum

    bcand = _active(s, S) & (pre >= pos)
    # active slots have index < count, so the min picks the first candidate
    # when one exists and falls back to count (append) otherwise
    idx_b = jnp.min(jnp.where(bcand, i, s["count"]))

    shift = jnp.where(has_inside, 2, 1).astype(jnp.int32)
    new_count = s["count"] + shift
    would_overflow = new_count > S

    new_slot = jnp.where(has_inside, j + 1, idx_b)
    is_new = i == new_slot
    is_right = has_inside & (i == new_slot + 1)   # split right piece
    is_left = has_inside & (i == j)               # split left piece
    below = i < new_slot

    out = {}
    for k in _PLANES:
        shifted = jnp.where(has_inside, jnp.roll(s[k], 2), jnp.roll(s[k], 1))
        out[k] = jnp.where(below, s[k], shifted)

    # base values at is_right are the containing slot's (via roll-by-2)
    out["length"] = jnp.where(
        is_new, length,
        jnp.where(is_left, off,
                  jnp.where(is_right, out["length"] - off, out["length"])))
    out["handle_off"] = jnp.where(
        is_new, 0,
        jnp.where(is_right, out["handle_off"] + off, out["handle_off"]))
    out["handle_op"] = jnp.where(is_new, handle, out["handle_op"])
    out["seq"] = jnp.where(is_new, seq, out["seq"])
    out["client"] = jnp.where(is_new, client_idx, out["client"])
    out["removed_seq"] = jnp.where(is_new, NOT_REMOVED, out["removed_seq"])
    out["removers"] = jnp.where(is_new, 0, out["removers"])

    # property planes (one (S,) plane per key): same roll, split right
    # piece inherits the containing slot's props via roll-by-2; new
    # segments carry none (host inserts-with-props are expressed as insert
    # + annotate at one seq). with_props=False (host knows no annotate
    # ever touched this store): all-zero planes are permutation-invariant,
    # skip the movement — ~35% of the kernel's HBM traffic.
    data_keys = _PLANES
    if with_props:
        pkeys = _prop_keys(s)
        data_keys = _PLANES + pkeys
        for pk in pkeys:
            pshift = jnp.where(has_inside, jnp.roll(s[pk], 2),
                               jnp.roll(s[pk], 1))
            pv = jnp.where(below, s[pk], pshift)
            out[pk] = jnp.where(is_new, 0, pv)
        if "prop_val" in s:  # stacked (S, K) variant (megadoc XLA path)
            data_keys = data_keys + ("prop_val",)
            pshift3 = jnp.where(has_inside,
                                jnp.roll(s["prop_val"], 2, axis=0),
                                jnp.roll(s["prop_val"], 1, axis=0))
            pv3 = jnp.where(below[:, None], s["prop_val"], pshift3)
            out["prop_val"] = jnp.where(is_new[:, None], 0, pv3)
    elif "prop_val" in s:
        out["prop_val"] = s["prop_val"]
        data_keys = _PLANES + ("prop_val",)

    # overflow: leave the doc untouched, set the sticky flag
    res = {k: jnp.where(would_overflow, s[k], out[k]) for k in data_keys}
    res["count"] = jnp.where(would_overflow, s["count"], new_count)
    res["overflow"] = jnp.where(would_overflow, 1, s["overflow"])
    return res


def _split_at(s, p, ref_seq, client_idx, with_props=True):
    """Split the visible segment strictly containing perspective position p."""
    S = s["seq"].shape[0]
    i = _iota(S)
    vis = _visible(s, ref_seq, client_idx)
    pre, end = _prefix(s, vis)
    inside = vis & (pre < p) & (p < end)
    has_inside = jnp.any(inside)
    j = jnp.min(jnp.where(inside, i, S))             # first-true index
    off = p - jnp.sum(jnp.where(inside, pre, 0))     # pre[j], one-hot sum

    new_count = s["count"] + 1
    would_overflow = new_count > S
    do = has_inside & ~would_overflow

    # gather-free: roll(s, 1) already carries slot j's values to j+1
    is_left = i == j
    is_right = i == j + 1
    out = {}
    for k in _PLANES:
        out[k] = jnp.where(i <= j, s[k], jnp.roll(s[k], 1))
    out["length"] = jnp.where(
        is_left, off,
        jnp.where(is_right, out["length"] - off, out["length"]))
    out["handle_off"] = jnp.where(
        is_right, out["handle_off"] + off, out["handle_off"])
    data_keys = _PLANES
    if with_props:
        pkeys = _prop_keys(s)
        data_keys = _PLANES + pkeys
        for pk in pkeys:
            out[pk] = jnp.where(i <= j, s[pk], jnp.roll(s[pk], 1))
        if "prop_val" in s:  # stacked (S, K) variant (megadoc XLA path)
            data_keys = data_keys + ("prop_val",)
            out["prop_val"] = jnp.where(
                (i <= j)[:, None], s["prop_val"],
                jnp.roll(s["prop_val"], 1, axis=0))
    elif "prop_val" in s:
        out["prop_val"] = s["prop_val"]
        data_keys = _PLANES + ("prop_val",)

    res = {k: jnp.where(do, out[k], s[k]) for k in data_keys}
    res["count"] = jnp.where(do, new_count, s["count"])
    res["overflow"] = jnp.where(has_inside & would_overflow, 1, s["overflow"])
    return res


PROP_HANDLE_BITS = 20  # a2 for annotate = key plane index << 20 | value handle


def _range_one(s, kind, start, end_pos, packed, seq, client_idx, ref_seq,
               with_props=True):
    """Apply one remove OR annotate to one doc — both are "two splits at the
    perspective boundaries + mark the visible segments strictly inside", so
    they share the expensive split passes and differ only in the cheap mark.

    Remove: only segments visible to the remover are marked — concurrently
    inserted text inside the range survives, overlapping removes keep the
    earliest acked removal seq and accumulate remover bits.

    Annotate: per-key last-sequenced-writer-wins (reference: merge-tree
    annotate). ``packed`` = key plane index << PROP_HANDLE_BITS | value
    handle; handle 0 deletes the key. Scan order is seq order, so a plain
    overwrite of the key's plane on visible targets realises LWW."""
    s = _split_at(s, start, ref_seq, client_idx, with_props)
    s = _split_at(s, end_pos, ref_seq, client_idx, with_props)
    vis = _visible(s, ref_seq, client_idx)
    pre, endp = _prefix(s, vis)
    target = vis & (pre >= start) & (endp <= end_pos) & (s["length"] > 0)

    # int(): IntEnum members are not literal-eligible on older jax (exact-
    # type check) and become captured constants, which pallas<0.5 rejects
    is_rem = kind == int(OpKind.STR_REMOVE)
    bit = jnp.where(client_idx >= 0,
                    (1 << jnp.clip(client_idx, 0, MAX_CLIENTS - 1)), 0)
    out = dict(s)
    out["removed_seq"] = jnp.where(
        target & is_rem, jnp.minimum(s["removed_seq"], seq),
        s["removed_seq"])
    out["removers"] = jnp.where(target & is_rem, s["removers"] | bit,
                                s["removers"])

    if with_props:
        key_idx = packed >> PROP_HANDLE_BITS
        handle = packed & ((1 << PROP_HANDLE_BITS) - 1)
        is_ann = target & (kind == int(OpKind.STR_ANNOTATE))
        for ki, pk in enumerate(_prop_keys(s)):
            out[pk] = jnp.where(is_ann & (key_idx == ki), handle, s[pk])
        if "prop_val" in s:  # stacked (S, K) variant (megadoc XLA path)
            K = s["prop_val"].shape[1]
            sel = is_ann[:, None] & (jnp.arange(K)[None, :] == key_idx)
            out["prop_val"] = jnp.where(sel, handle, s["prop_val"])
    return out




# ------------------------------------------------------------- batched apply

def _state_dict(state: StringState):
    return {
        "seq": state.seq, "client": state.client,
        "removed_seq": state.removed_seq, "removers": state.removers,
        "length": state.length, "handle_op": state.handle_op,
        "handle_off": state.handle_off, "prop_val": state.prop_val,
        "count": state.count, "overflow": state.overflow,
    }


def apply_string_batch(state: StringState, kind, a0, a1, a2, seq, client,
                       ref_seq, with_props: bool = True) -> StringState:
    """Apply a dense (D, O) batch of sequenced merge-tree ops.

    kind/a0/a1/a2/seq/client/ref_seq: (D, O) int32 planes. Per doc, ops apply
    in ascending op index (the sequencer's total order); NOOP pads.
    STR_INSERT: a0=pos, a1=len, a2=payload handle. STR_REMOVE: a0=start,
    a1=end. STR_ANNOTATE: a0=start, a1=end, a2=key plane << 20 | value
    handle.

    with_props=False (static): the host guarantees no annotate has ever
    touched this state, so the all-zero property planes are permutation-
    invariant and all prop movement is skipped (the planes thread through
    the scan untouched).
    """
    sd = _state_dict(state)
    K = state.prop_val.shape[2]
    if with_props:
        # split (D, S, K) into K 2-D planes for the helpers (see _prop_keys)
        pv = sd.pop("prop_val")
        for i in range(K):
            sd[f"prop{i}"] = pv[:, :, i]

    def step(carry, op):
        k, p0, p1, p2, sq, cl, rs = op

        ins = jax.vmap(functools.partial(_insert_one, with_props=with_props)
                       )(carry, p0, p1, p2, sq, cl, rs)
        rng = jax.vmap(functools.partial(_range_one, with_props=with_props)
                       )(carry, k, p0, p1, p2, sq, cl, rs)

        def pick(key):
            tail = (1,) * (carry[key].ndim - 1)
            is_ins = (k == OpKind.STR_INSERT).reshape((-1,) + tail)
            is_rng = ((k == OpKind.STR_REMOVE) |
                      (k == OpKind.STR_ANNOTATE)).reshape((-1,) + tail)
            return jnp.where(is_ins, ins[key],
                             jnp.where(is_rng, rng[key], carry[key]))

        return {key: pick(key) for key in carry}, None

    ops = (kind.T, a0.T, a1.T, a2.T, seq.T, client.T, ref_seq.T)  # (O, D)
    out, _ = jax.lax.scan(step, sd, ops)
    if with_props:
        out["prop_val"] = jnp.stack(
            [out.pop(f"prop{i}") for i in range(K)], axis=-1)
    return StringState(**out)


apply_string_batch_jit = jax.jit(apply_string_batch, donate_argnums=0,
                                 static_argnames=("with_props",))


def compact_string_state(state: StringState, min_seq,
                         with_props: bool = True) -> StringState:
    """Zamboni on device: drop tombstones whose removal is acked at or below
    minSeq (reference: merge-tree zamboni; SURVEY.md §7.4 "compaction kernel
    keyed on MSN"). Stable partition keeps document order. min_seq: (D,)."""
    sd = _state_dict(state)
    S = state.seq.shape[1]

    # Gather-free stable partition: sort every plane together on the
    # drop-key with one multi-operand lax.sort (TPU sort network), instead
    # of argsort + per-plane gather (which lowers to scalar loops).
    active = jnp.arange(S)[None, :] < state.count[:, None]
    keep = active & ~(state.removed_seq <= min_seq[:, None])
    key = (~keep).astype(jnp.int32)
    K = state.prop_val.shape[2] if with_props else 0
    planes = [sd[k] for k in _PLANES] + \
        [state.prop_val[:, :, i] for i in range(K)]
    sorted_ = jax.lax.sort([key] + planes, dimension=1, is_stable=True,
                           num_keys=1)
    out = dict(zip(_PLANES, sorted_[1:1 + len(_PLANES)]))
    out["prop_val"] = jnp.stack(sorted_[1 + len(_PLANES):], axis=2) \
        if with_props else state.prop_val  # all-zero: permutation-invariant
    out["count"] = jnp.sum(keep.astype(jnp.int32), axis=1)
    out["overflow"] = state.overflow
    return StringState(**out)


# jitted zamboni: an un-jitted call runs dozens of eager dispatches —
# ruinous over a remote-tunnel device link (each pays the RTT)
compact_string_state_jit = jax.jit(compact_string_state, donate_argnums=0,
                                   static_argnames=("with_props",))


def string_state_digest(state: StringState) -> jax.Array:
    """Per-doc content digest, invariant to split boundaries: for a live run
    (handle_op, handle_off) at visible position pos, (handle_off - pos) is
    identical for every piece of the same insert, so the per-slot mix sums to
    the same value however the run is physically split."""
    S = state.seq.shape[1]
    active = jnp.arange(S)[None, :] < state.count[:, None]
    live = active & (state.removed_seq == NOT_REMOVED)
    pl = jnp.where(live, state.length, 0)
    pre = jnp.cumsum(pl, axis=1) - pl
    mix = (state.handle_op * 1000003 + (state.handle_off - pre) * 8191) * pl
    return jnp.sum(jnp.where(live, mix, 0), axis=1) + jnp.sum(pl, axis=1)
