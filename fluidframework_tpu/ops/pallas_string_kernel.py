"""Pallas TPU kernel for the batched merge-tree apply: VMEM-resident op loop.

The XLA path (``apply_string_batch``) scans the op axis with the state planes
round-tripping through HBM on every op: one 64-op batch moves the whole
(D, S) state 128 times. This kernel tiles the doc axis across the grid,
loads one tile's planes into VMEM ONCE, applies the entire op batch with a
``fori_loop`` inside the kernel, and writes the planes back ONCE — turning
O(ops) HBM traffic into O(1) per batch. The per-op math is literally the
same ``_insert_one`` / ``_range_one`` helpers as the XLA path (vmapped over
the tile's docs), so semantics are shared by construction, not re-derived.

Two specializations: no-props (stores that have never seen an annotate —
``TensorStringStore._has_props`` False, the mode the north-star benchmark
measures; property planes thread through untouched host-side) and props
(``with_props=True``: the K property planes ride along in VMEM, so
annotate-heavy workloads — rich text, config #5 — stay on the fused path).

VMEM budget per tile: 7 planes × T×S int32 + op planes × T×O + live
temporaries — T=128, S=384 measures fastest on v5e (2.2× the XLA scan at
bench shapes); T=256 exceeds VMEM and fails to compile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .merge_tree_kernel import (
    _PLANES, StringState, _cumsum, _insert_one, _range_one,
)
from .schema import OpKind

_OPS = 7      # kind, a0, a1, a2, seq, client, ref_seq
_NP = len(_PLANES)


def _compact(c, min_seq, keys=_PLANES):
    """In-VMEM zamboni: stable stream compaction by bit-decomposed shifts.

    Drop slots whose removal is acked at or below min_seq. Each surviving
    slot must move left by d = (dropped slots before it) — non-decreasing
    in slot index, and any two kept slots with displacement difference δ
    are at least δ+1 apart, so shifting every slot whose d has bit b by
    2^b (LSB→MSB) never collides. log2(S) roll+select passes, no sort, no
    gather. Vacated slots are zeroed (removed_seq=NOT_REMOVED) — like the
    XLA sort path, slots at or beyond count are semantically ignored.
    ``keys`` lists the 2-D (T, S) planes to move (props mode adds the
    unstacked property planes)."""
    from ..core.constants import NOT_REMOVED
    S = c["seq"].shape[-1]
    active = _iota2(c["seq"].shape) < c["count"][:, None]
    keep = active & ~(c["removed_seq"] <= min_seq[:, None])
    # dropped-before count: exclusive prefix sum of ~keep over active slots
    dropped = jnp.where(active & ~keep, 1, 0)
    d = _excl_cumsum_last(dropped)

    occ = keep
    planes = {k: c[k] for k in keys}
    idx = _iota2(c["seq"].shape)
    step = 1
    while step < S:
        b_set = occ & (((d // step) % 2) == 1)
        # mask the roll's wraparound: position p receives from p+step only
        # when p+step is in range (the head wrapping to the tail must not
        # masquerade as an incoming element). Roll an int32 mask — Mosaic
        # cannot roll i1 vectors.
        b_set_i = jnp.where(b_set, 1, 0)
        moves_in = (jnp.roll(b_set_i, -step, axis=-1) == 1) & \
            (idx < S - step)
        stays = occ & ~b_set
        for k in keys:
            incoming = jnp.roll(planes[k], -step, axis=-1)
            planes[k] = jnp.where(moves_in, incoming,
                                  jnp.where(stays, planes[k], 0))
        d = jnp.where(moves_in, jnp.roll(d, -step, axis=-1), d)
        occ = moves_in | stays
        step *= 2
    planes["removed_seq"] = jnp.where(occ, planes["removed_seq"],
                                      NOT_REMOVED)
    out = dict(c)
    out.update(planes)
    out["count"] = jnp.sum(keep.astype(jnp.int32), axis=-1)
    return out


def _iota2(shape):
    return jax.lax.broadcasted_iota(jnp.int32, shape, len(shape) - 1)


def _excl_cumsum_last(x):
    """Exclusive prefix sum along the last axis: the shared Hillis-Steele
    inclusive scan, shifted right by one."""
    c = _cumsum(x)
    return jnp.where(_iota2(x.shape) == 0, 0, jnp.roll(c, 1, axis=-1))


def _kernel(*refs, compact: bool, n_props: int):
    """n_props=0: the no-props specialization (property planes untouched
    host-side). n_props=K: the K property planes ride along in VMEM as K
    extra (T, S) refs, moved by the same split/shift/compact passes."""
    if compact:
        ms_ref, refs = refs[0], refs[1:]
    np_ = _NP + n_props
    op_refs = refs[:_OPS]
    plane_refs = refs[_OPS:_OPS + np_]
    cnt_ref, ovf_ref = refs[_OPS + np_:_OPS + np_ + 2]
    out_plane_refs = refs[_OPS + np_ + 2:_OPS + 2 * np_ + 2]
    out_cnt_ref, out_ovf_ref = refs[_OPS + 2 * np_ + 2:]
    with_props = n_props > 0

    n_ops = op_refs[0].shape[1]
    ops = tuple(r[:] for r in op_refs)              # each (T, O), VMEM
    lane = jax.lax.broadcasted_iota(jnp.int32, ops[0].shape, 1)
    carry = dict(zip(_PLANES, (r[:] for r in plane_refs[:_NP])))
    if with_props:
        # K separate (T, S) planes — a stacked (T, S, K) would lane-pad
        # the minor dim to 128 in VMEM (~32× bloat); see _prop_keys
        for i in range(n_props):
            carry[f"prop{i}"] = plane_refs[_NP + i][:]
    else:
        # dummy 1-wide prop plane: with_props=False helpers pass it through
        carry["prop_val"] = jnp.zeros(carry["seq"].shape + (1,), jnp.int32)
    carry["count"] = cnt_ref[:, 0]
    carry["overflow"] = ovf_ref[:, 0]

    def body(o, c):
        # one-hot column extraction: Mosaic supports neither dynamic_slice
        # on values nor unaligned dynamic lane indexing on refs
        take = lambda x: jnp.sum(jnp.where(lane == o, x, 0), axis=1)
        k, p0, p1, p2, sq, cl, rs = (take(x) for x in ops)
        ins = jax.vmap(functools.partial(_insert_one, with_props=with_props)
                       )(c, p0, p1, p2, sq, cl, rs)
        rng = jax.vmap(functools.partial(_range_one, with_props=with_props)
                       )(c, k, p0, p1, p2, sq, cl, rs)

        def pick(key):
            tail = (1,) * (c[key].ndim - 1)
            # int(): IntEnum members are not literal-eligible on older jax
            # (exact-type check) and would be captured as kernel constants,
            # which pallas<0.5 rejects
            is_ins = (k == int(OpKind.STR_INSERT)).reshape((-1,) + tail)
            is_rng = ((k == int(OpKind.STR_REMOVE)) |
                      (k == int(OpKind.STR_ANNOTATE))).reshape((-1,) + tail)
            return jnp.where(is_ins, ins[key],
                             jnp.where(is_rng, rng[key], c[key]))

        return {key: pick(key) for key in c}

    out = jax.lax.fori_loop(0, n_ops, body, carry)
    prop_keys = tuple(f"prop{i}" for i in range(n_props))
    if compact:
        out = _compact(out, ms_ref[:, 0], keys=_PLANES + prop_keys)
    for name, ref in zip(_PLANES + prop_keys, out_plane_refs):
        ref[:] = out[name]
    out_cnt_ref[:, 0] = out["count"]
    out_ovf_ref[:, 0] = out["overflow"]


def apply_string_batch_pallas(state: StringState, kind, a0, a1, a2, seq,
                              client, ref_seq, min_seq=None, tile: int = 128,
                              interpret: bool = False,
                              with_props: bool = False) -> StringState:
    """Drop-in equivalent of ``apply_string_batch``, optionally fused with
    zamboni: pass ``min_seq`` (D,) to compact each doc inside the kernel
    epilogue while the planes are still in VMEM — one dispatch, one HBM
    round-trip for apply + compact.

    ``with_props=False`` is the annotate-free specialization (property
    planes thread through untouched host-side); ``with_props=True`` loads
    the K property planes into VMEM alongside the rest, so annotate-bearing
    stores stay on the fused path too.

    D must divide by ``tile``; S should be a multiple of 128 (lane width).
    ``interpret=True`` runs the Pallas interpreter (CPU tests)."""
    D, S = state.seq.shape
    O = kind.shape[1]
    assert D % tile == 0, f"doc count {D} not divisible by tile {tile}"
    compact = min_seq is not None
    K = state.prop_val.shape[2] if with_props else 0
    np_ = _NP + K

    op_spec = pl.BlockSpec((tile, O), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    plane_spec = pl.BlockSpec((tile, S), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((tile, 1), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)

    n_lead = 1 if compact else 0
    grid_spec = pl.GridSpec(
        grid=(D // tile,),
        in_specs=[col_spec] * n_lead + [op_spec] * _OPS
        + [plane_spec] * np_ + [col_spec] * 2,
        out_specs=tuple([plane_spec] * np_ + [col_spec] * 2),
    )
    out_shape = tuple(
        [jax.ShapeDtypeStruct((D, S), jnp.int32)] * np_
        + [jax.ShapeDtypeStruct((D, 1), jnp.int32)] * 2)

    # donate the state planes into the outputs (in-place update in HBM)
    aliases = {n_lead + _OPS + i: i for i in range(np_ + 2)}
    lead = (jnp.asarray(min_seq, jnp.int32)[:, None],) if compact else ()
    prop_in = tuple(state.prop_val[:, :, i] for i in range(K))
    outs = pl.pallas_call(
        functools.partial(_kernel, compact=compact, n_props=K),
        grid_spec=grid_spec, out_shape=out_shape,
        input_output_aliases=aliases, interpret=interpret,
    )(*lead, kind, a0, a1, a2, seq, client, ref_seq,
      *(getattr(state, k) for k in _PLANES), *prop_in,
      state.count[:, None], state.overflow[:, None])

    planes = dict(zip(_PLANES, outs[:_NP]))
    prop_val = jnp.stack(outs[_NP:np_], axis=-1) if with_props \
        else state.prop_val
    return StringState(**planes, prop_val=prop_val,
                       count=outs[np_][:, 0], overflow=outs[np_ + 1][:, 0])
