"""Pallas TPU kernel for the batched merge-tree apply: VMEM-resident op loop.

The XLA path (``apply_string_batch``) scans the op axis with the state planes
round-tripping through HBM on every op: one 64-op batch moves the whole
(D, S) state 128 times. This kernel tiles the doc axis across the grid,
loads one tile's planes into VMEM ONCE, applies the entire op batch with a
``fori_loop`` inside the kernel, and writes the planes back ONCE — turning
O(ops) HBM traffic into O(1) per batch. The per-op math is literally the
same ``_insert_one`` / ``_range_one`` helpers as the XLA path (vmapped over
the tile's docs), so semantics are shared by construction, not re-derived.

Serving (no-props) path only: stores that have never seen an annotate
(``TensorStringStore._has_props`` False, the mode the north-star benchmark
measures). Property planes thread through untouched host-side.

VMEM budget per tile: 7 planes × T×S int32 + op planes × T×O + live
temporaries — T=128, S=384 measures fastest on v5e (2.2× the XLA scan at
bench shapes); T=256 exceeds VMEM and fails to compile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .merge_tree_kernel import (
    _PLANES, StringState, _insert_one, _range_one,
)
from .schema import OpKind

_OPS = 7      # kind, a0, a1, a2, seq, client, ref_seq
_NP = len(_PLANES)


def _kernel(*refs):
    op_refs = refs[:_OPS]
    plane_refs = refs[_OPS:_OPS + _NP]
    cnt_ref, ovf_ref = refs[_OPS + _NP:_OPS + _NP + 2]
    out_plane_refs = refs[_OPS + _NP + 2:_OPS + 2 * _NP + 2]
    out_cnt_ref, out_ovf_ref = refs[_OPS + 2 * _NP + 2:]

    n_ops = op_refs[0].shape[1]
    ops = tuple(r[:] for r in op_refs)              # each (T, O), VMEM
    lane = jax.lax.broadcasted_iota(jnp.int32, ops[0].shape, 1)
    carry = dict(zip(_PLANES, (r[:] for r in plane_refs)))
    # dummy 1-wide prop plane: the with_props=False helpers pass it through
    carry["prop_val"] = jnp.zeros(carry["seq"].shape + (1,), jnp.int32)
    carry["count"] = cnt_ref[:, 0]
    carry["overflow"] = ovf_ref[:, 0]

    def body(o, c):
        # one-hot column extraction: Mosaic supports neither dynamic_slice
        # on values nor unaligned dynamic lane indexing on refs
        take = lambda x: jnp.sum(jnp.where(lane == o, x, 0), axis=1)
        k, p0, p1, p2, sq, cl, rs = (take(x) for x in ops)
        ins = jax.vmap(functools.partial(_insert_one, with_props=False)
                       )(c, p0, p1, p2, sq, cl, rs)
        rng = jax.vmap(functools.partial(_range_one, with_props=False)
                       )(c, k, p0, p1, p2, sq, cl, rs)

        def pick(key):
            tail = (1,) * (c[key].ndim - 1)
            is_ins = (k == OpKind.STR_INSERT).reshape((-1,) + tail)
            is_rng = ((k == OpKind.STR_REMOVE) |
                      (k == OpKind.STR_ANNOTATE)).reshape((-1,) + tail)
            return jnp.where(is_ins, ins[key],
                             jnp.where(is_rng, rng[key], c[key]))

        return {key: pick(key) for key in c}

    out = jax.lax.fori_loop(0, n_ops, body, carry)
    for name, ref in zip(_PLANES, out_plane_refs):
        ref[:] = out[name]
    out_cnt_ref[:, 0] = out["count"]
    out_ovf_ref[:, 0] = out["overflow"]


def apply_string_batch_pallas(state: StringState, kind, a0, a1, a2, seq,
                              client, ref_seq, tile: int = 128,
                              interpret: bool = False) -> StringState:
    """Drop-in equivalent of ``apply_string_batch(..., with_props=False)``.

    D must divide by ``tile``; S should be a multiple of 128 (lane width).
    ``interpret=True`` runs the Pallas interpreter (CPU tests)."""
    D, S = state.seq.shape
    O = kind.shape[1]
    assert D % tile == 0, f"doc count {D} not divisible by tile {tile}"

    op_spec = pl.BlockSpec((tile, O), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    plane_spec = pl.BlockSpec((tile, S), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((tile, 1), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)

    grid_spec = pl.GridSpec(
        grid=(D // tile,),
        in_specs=[op_spec] * _OPS + [plane_spec] * _NP + [col_spec] * 2,
        out_specs=tuple([plane_spec] * _NP + [col_spec] * 2),
    )
    out_shape = tuple(
        [jax.ShapeDtypeStruct((D, S), jnp.int32)] * _NP
        + [jax.ShapeDtypeStruct((D, 1), jnp.int32)] * 2)

    # donate the state planes into the outputs (in-place update in HBM)
    aliases = {_OPS + i: i for i in range(_NP + 2)}
    outs = pl.pallas_call(
        _kernel, grid_spec=grid_spec, out_shape=out_shape,
        input_output_aliases=aliases, interpret=interpret,
    )(kind, a0, a1, a2, seq, client, ref_seq,
      *(getattr(state, k) for k in _PLANES),
      state.count[:, None], state.overflow[:, None])

    planes = dict(zip(_PLANES, outs[:_NP]))
    return StringState(**planes, prop_val=state.prop_val,
                       count=outs[_NP][:, 0], overflow=outs[_NP + 1][:, 0])
