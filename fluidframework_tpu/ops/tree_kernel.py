"""Batched SharedTree op-apply kernel: the tree DDS on device.

Reference counterpart: ``@fluidframework/tree`` — upstream's largest DDS
(SURVEY.md §2.6); host oracle: ``models.shared_tree`` (the merge-rule spec).
The oracle's id-anchored design was chosen FOR this kernel (its module
docstring promises the "node-id-indexed struct-of-arrays table" built here):
because every edit targets stable node ids, the device never resolves
positions — merge is total-order apply of id math.

Representation (D docs × N node slots, all int32):

- ``node_id``   interned id handle (0 = free slot). Slot position carries NO
  meaning — sibling ORDER lives in a doubly-linked list (``prev_sib`` /
  ``next_sib`` id handles, 0 = end), so an insert-after is a pointer splice
  (three one-hot writes), never a shift, and the struct never moves.
- ``parent`` / ``field``   attachment (id handle / field-name handle).
- ``value`` / ``type_``    LWW value handle / node type handle.
- ``created_seq``          the sequenced op that created the slot — the
  nested-insert dependency test (below).

Merge rules ON DEVICE (bit-for-bit the oracle's):

- insert: parent must exist; id must be absent; a dead/foreign ``after``
  anchor (not a live sibling under (parent, field)) degrades to
  start-of-field; later-sequenced concurrent inserts land closer to the
  anchor (list-head splice order gives this for free).
- remove: detach + delete the whole subtree — transitive closure by
  iterative parent-marking (an (N×N) masked compare per wave, no gathers);
  root immutable.
- move: dropped if node/destination missing or the destination lies inside
  the moved subtree (cycle); else splice out + splice in.
- setValue: last-sequenced-writer-wins (scan order is seq order).

Group atomicity WITHOUT cross-record control flow:

- A multi-node/nested insert expands host-side into per-node records that
  share the op's seq. ``INS_BEGIN`` resets the per-doc ``ok_ins`` flag;
  ``INS_GUARD_ABSENT(id)`` ANDs it with "id is absent" (one per top-level
  spec node — any collision drops the whole insert, as the oracle does).
  A NESTED record additionally requires its parent slot's
  ``created_seq == seq`` — "my parent was created by THIS op" — which
  reproduces the oracle's skip-the-subtree rule when a nested id survived
  elsewhere.
- A transaction wraps its sub-edits with ``TXN_BEGIN`` +
  ``TXN_GUARD_EXISTS(id)`` records gating a second flag ``ok_txn``; every
  record in the group applies only when both flags hold, so a failed
  constraint drops the group atomically while admitted sub-edits still
  degrade individually.

Capacity: an insert finding no free slot sets the doc's sticky overflow
flag and leaves the doc unchanged (same escape hatch as the string kernel).
"""

from __future__ import annotations

import dataclasses
import enum
import functools

import jax
import jax.numpy as jnp


class TreeOpKind(enum.IntEnum):
    NOOP = 0
    INS_BEGIN = 1         # reset ok_ins
    INS_GUARD_ABSENT = 2  # ok_ins &= (node absent)
    TXN_BEGIN = 3         # reset ok_txn AND ok_ins
    TXN_GUARD_EXISTS = 4  # ok_txn &= (node present)
    INSERT = 5            # node,parent,after,field,value,type_; meta bit 0:
    #                       nested (require parent.created_seq == seq)
    REMOVE = 6            # node
    MOVE = 7              # node,parent,after,field
    SET_VALUE = 8         # node,value
    # "solo" kinds: a COMPLETE one-record op — same math as the base kind
    # (solo − 4) but ignoring the group flags (a standalone edit's implicit
    # TXN_BEGIN reset would make ok == 1 anyway). They exist so the volume
    # paths (flat inserts, standalone removes/sets) cost ONE scan step per
    # op instead of a begin/guard preamble. Never valid inside a
    # transaction group (they would bypass its constraint gate).
    INSERT_SOLO = 9
    REMOVE_SOLO = 10
    MOVE_SOLO = 11
    SET_SOLO = 12
    # fused TXN_BEGIN + TXN_GUARD_EXISTS(node): resets both flags, then
    # ok_txn &= exists — the first constraint of every transaction rides
    # its begin record (one record less on the wire per transaction)
    TXN_BEGIN_EXISTS = 13


META_NESTED = 1

ROOT_HANDLE = 1  # every doc's root node id handle (host interner reserves it)

_TREE_PLANES = ("node_id", "parent", "field", "value", "type_",
                "prev_sib", "next_sib", "created_seq")


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TreeState:
    node_id: jax.Array      # (D, N) id handle, 0 = free
    parent: jax.Array       # (D, N) parent id handle (0 for root)
    field: jax.Array        # (D, N) field handle
    value: jax.Array        # (D, N) value handle
    type_: jax.Array        # (D, N) type handle
    prev_sib: jax.Array     # (D, N) id handle, 0 = field head
    next_sib: jax.Array     # (D, N) id handle, 0 = field tail
    created_seq: jax.Array  # (D, N)
    overflow: jax.Array     # (D,) sticky

    @staticmethod
    def create(n_docs: int, capacity: int) -> "TreeState":
        z = lambda: jnp.zeros((n_docs, capacity), jnp.int32)
        st = TreeState(node_id=z(), parent=z(), field=z(), value=z(),
                       type_=z(), prev_sib=z(), next_sib=z(),
                       created_seq=z(),
                       overflow=jnp.zeros((n_docs,), jnp.int32))
        # slot 0 of every doc is the root
        st.node_id = st.node_id.at[:, 0].set(ROOT_HANDLE)
        return st


# ----------------------------------------------------------- single-doc math
# All helpers operate on one doc's (N,) planes in dict ``s`` (+ scalar
# carry flags) and are vmapped over the doc axis by the batch step.

def _exists(s, nid):
    """Is id handle ``nid`` present (and non-zero)?"""
    return (nid != 0) & jnp.any(s["node_id"] == nid)


def _slot_value(s, nid, plane):
    """plane[slot_of(nid)] via one-hot reduction (0 when absent)."""
    return jnp.sum(jnp.where(s["node_id"] == nid, s[plane], 0))


def _write_at_id(s, nid, plane, val):
    """plane[slot_of(nid)] = val (no-op when absent)."""
    return jnp.where(s["node_id"] == nid, val, s[plane])


def _subtree_mask(s, nid):
    """(N,) bool: slots inside the subtree rooted at id ``nid``.

    Iterative wave expansion: a slot joins when its parent's id is already
    marked. Each wave is one (N×N) masked compare — gather-free — and the
    loop runs until a wave adds nothing (≤ depth waves)."""
    live = s["node_id"] != 0
    mark0 = live & (s["node_id"] == nid)

    def cond(carry):
        mark, changed = carry
        return changed

    def body(carry):
        mark, _ = carry
        # parent[i] ∈ marked ids ⇔ ∃j: marked[j] & node_id[j] == parent[i]
        hit = jnp.any(mark[None, :] & (s["node_id"][None, :] ==
                                       s["parent"][:, None]), axis=1)
        new = mark | (live & hit & (s["parent"] != 0))
        return (new, jnp.any(new != mark))

    mark, _ = jax.lax.while_loop(cond, body, (mark0, jnp.any(mark0)))
    return mark


def _splice_out(s, nid):
    """Unlink ``nid`` from its sibling list: neighbors bridge over it, and
    its own attachment planes reset (a detached node must not match any
    head/anchor search on the intermediate state)."""
    prev = _slot_value(s, nid, "prev_sib")
    nxt = _slot_value(s, nid, "next_sib")
    me = s["node_id"] == nid
    out = dict(s)
    # next[prev] = next ; prev[next] = prev (one-hot writes, 0-guarded)
    out["next_sib"] = jnp.where((s["node_id"] == prev) & (prev != 0), nxt,
                                s["next_sib"])
    out["prev_sib"] = jnp.where((s["node_id"] == nxt) & (nxt != 0), prev,
                                s["prev_sib"])
    for k in ("parent", "field", "prev_sib", "next_sib"):
        out[k] = jnp.where(me, 0, out[k])
    return out, prev, nxt


def _head_of(s, parent, field):
    """Id handle of the first child in (parent, field), else 0."""
    is_head = (s["node_id"] != 0) & (s["parent"] == parent) & \
        (s["field"] == field) & (s["prev_sib"] == 0)
    return jnp.sum(jnp.where(is_head, s["node_id"], 0))


def _attach(s, nid, parent, field, after):
    """Splice ``nid`` (already materialized in a slot) into the sibling
    list: after a live same-(parent, field) anchor, else at field head."""
    anchor_ok = (after != 0) & _exists(s, after) & \
        (_slot_value(s, after, "parent") == parent) & \
        (_slot_value(s, after, "field") == field)
    prev = jnp.where(anchor_ok, after, 0)
    nxt = jnp.where(anchor_ok, _slot_value(s, after, "next_sib"),
                    _head_of(s, parent, field))
    nxt = jnp.where(nxt == nid, 0, nxt)  # self-link guard (fresh head)
    out = dict(s)
    me = out["node_id"] == nid
    out["parent"] = jnp.where(me, parent, out["parent"])
    out["field"] = jnp.where(me, field, out["field"])
    out["prev_sib"] = jnp.where(me, prev, out["prev_sib"])
    out["next_sib"] = jnp.where(me, nxt, out["next_sib"])
    # neighbors point at me
    out["next_sib"] = jnp.where((out["node_id"] == prev) & (prev != 0), nid,
                                out["next_sib"])
    out["prev_sib"] = jnp.where((out["node_id"] == nxt) & (nxt != 0), nid,
                                out["prev_sib"])
    return out


def _apply_insert(s, node, parent, after, field, value, type_, seq, nested,
                  ok):
    parent_ok = _exists(s, parent) | (parent == ROOT_HANDLE)
    dep_ok = jnp.where(
        nested, _slot_value(s, parent, "created_seq") == seq,
        True)
    do = ok & parent_ok & ~_exists(s, node) & dep_ok & (node != 0)

    free = (s["node_id"] == 0)
    n = s["node_id"].shape[0]
    idx = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)[0]
    slot = jnp.min(jnp.where(free, idx, n))
    would_overflow = do & (slot >= n)
    do = do & (slot < n)

    is_slot = (idx == slot) & do
    out = dict(s)
    out["node_id"] = jnp.where(is_slot, node, s["node_id"])
    out["value"] = jnp.where(is_slot, value, s["value"])
    out["type_"] = jnp.where(is_slot, type_, s["type_"])
    out["created_seq"] = jnp.where(is_slot, seq, s["created_seq"])
    out["prev_sib"] = jnp.where(is_slot, 0, s["prev_sib"])
    out["next_sib"] = jnp.where(is_slot, 0, s["next_sib"])
    out["parent"] = jnp.where(is_slot, 0, s["parent"])
    out["field"] = jnp.where(is_slot, 0, s["field"])
    attached = _attach(out, node, parent, field, after)
    out = {k: jnp.where(do, attached[k], s[k]) for k in _TREE_PLANES}
    return out, would_overflow


def _apply_remove(s, node, ok):
    do = ok & _exists(s, node) & (node != ROOT_HANDLE)
    mask = _subtree_mask(s, node)
    spliced, _, _ = _splice_out(s, node)
    out = {}
    for k in _TREE_PLANES:
        cleared = jnp.where(mask, 0, spliced[k])
        out[k] = jnp.where(do, cleared, s[k])
    return out


def _apply_move(s, node, parent, after, field, ok):
    in_subtree = jnp.any(_subtree_mask(s, node) &
                         (s["node_id"] == parent))
    do = ok & _exists(s, node) & (node != ROOT_HANDLE) & \
        _exists(s, parent) & ~in_subtree
    spliced, _, _ = _splice_out(s, node)
    attached = _attach(spliced, node, parent, field, after)
    return {k: jnp.where(do, attached[k], s[k]) for k in _TREE_PLANES}


def _apply_set_value(s, node, value, ok):
    do = ok & _exists(s, node)
    out = dict(s)
    out["value"] = jnp.where(do & (s["node_id"] == node), value, s["value"])
    return out


# ------------------------------------------------------------- batched apply

def _one_record(c, k, solo, nd, pa, af, fi, va, ty, sq, me, *, structural):
    """Apply one record to one doc's planes. ``k`` is the BASE kind (solo
    already folded); ``structural`` statically includes the remove/move
    subtree math — the batch step gates it behind a column-level cond so
    insert/set-heavy batches never pay the (N×N) subtree walks."""
    s = {key: c[key] for key in _TREE_PLANES}
    begin = (k == TreeOpKind.TXN_BEGIN) | \
        (k == TreeOpKind.TXN_BEGIN_EXISTS)
    ok_ins = jnp.where((k == TreeOpKind.INS_BEGIN) | begin, 1, c["ok_ins"])
    ok_txn = jnp.where(begin, 1, c["ok_txn"])
    ok_ins = jnp.where(
        k == TreeOpKind.INS_GUARD_ABSENT,
        ok_ins & ~_exists(s, nd), ok_ins)
    ok_txn = jnp.where(
        (k == TreeOpKind.TXN_GUARD_EXISTS) |
        (k == TreeOpKind.TXN_BEGIN_EXISTS),
        ok_txn & _exists(s, nd), ok_txn)
    ok = (ok_ins & ok_txn).astype(bool) | solo

    ins, would_ovf = _apply_insert(
        s, nd, pa, af, fi, va, ty, sq, (me & META_NESTED) != 0,
        ok & (k == TreeOpKind.INSERT))
    sv = _apply_set_value(s, nd, va, ok & (k == TreeOpKind.SET_VALUE))
    if structural:
        rem = _apply_remove(s, nd, ok & (k == TreeOpKind.REMOVE))
        mov = _apply_move(s, nd, pa, af, fi, ok & (k == TreeOpKind.MOVE))

    out = {}
    for key in _TREE_PLANES:
        v = jnp.where(
            k == TreeOpKind.INSERT, ins[key],
            jnp.where(k == TreeOpKind.SET_VALUE, sv[key], s[key]))
        if structural:
            v = jnp.where(
                k == TreeOpKind.REMOVE, rem[key],
                jnp.where(k == TreeOpKind.MOVE, mov[key], v))
        out[key] = v
    out["overflow"] = jnp.where(
        (k == TreeOpKind.INSERT) & would_ovf, 1, c["overflow"])
    out["ok_ins"] = ok_ins
    out["ok_txn"] = ok_txn
    return out


def apply_tree_batch(state: TreeState, kind, node, parent, after, field,
                     value, type_, seq, meta) -> TreeState:
    """Apply a dense (D, O) batch of expanded tree records, per-doc in
    column order (the sequencer's total order); NOOP pads skip.

    Per record column the step dispatches one of three bodies via
    ``lax.cond``: all-NOOP columns (pow2 padding) are identity, columns
    with any remove/move run the full structural body, and everything
    else runs the light body (no subtree-mask while loops) — the batch
    only pays for the op classes it actually contains."""
    sd = {k: getattr(state, k) for k in _TREE_PLANES}
    sd["overflow"] = state.overflow
    sd["ok_ins"] = jnp.ones_like(state.overflow)
    sd["ok_txn"] = jnp.ones_like(state.overflow)

    def step(carry, op):
        k, nd, pa, af, fi, va, ty, sq, me = op
        solo = (k >= TreeOpKind.INSERT_SOLO) & (k <= TreeOpKind.SET_SOLO)
        base = jnp.where(solo, k - 4, k)
        heavy = jnp.any((base == TreeOpKind.REMOVE) |
                        (base == TreeOpKind.MOVE))
        any_op = jnp.any(k != TreeOpKind.NOOP)

        def run(structural):
            def go(c):
                return jax.vmap(functools.partial(
                    _one_record, structural=structural))(
                        c, base, solo, nd, pa, af, fi, va, ty, sq, me)
            return go

        out = jax.lax.cond(
            heavy, run(True),
            lambda c: jax.lax.cond(any_op, run(False), lambda c2: c2, c),
            carry)
        return out, None

    ops = tuple(x.T for x in (kind, node, parent, after, field, value,
                              type_, seq, meta))
    out, _ = jax.lax.scan(step, sd, ops)
    return TreeState(**{k: out[k] for k in _TREE_PLANES},
                     overflow=out["overflow"])


apply_tree_batch_jit = jax.jit(apply_tree_batch, donate_argnums=0)


def apply_tree_planes(state: TreeState, planes) -> TreeState:
    """Stacked-plane entry: ``planes`` is ONE (9, D, O) int32 buffer
    (kind, node, parent, after, field, value, type_, meta, seq) — a single
    contiguous host→device transfer per batch instead of nine."""
    return apply_tree_batch(
        state, planes[0], planes[1], planes[2], planes[3], planes[4],
        planes[5], planes[6], planes[8], planes[7])


apply_tree_planes_jit = jax.jit(apply_tree_planes, donate_argnums=0)


def apply_tree_wire(state: TreeState, cols, ids, vals, row, pos, base,
                    id_map, f_map, t_map, v_map, *, o: int) -> TreeState:
    """Compact-wire apply: width-coded record columns + batch-local table
    maps, expanded ON DEVICE (map gathers, dense scatter, per-record seq
    derivation). The host→device upload is the serving bottleneck (the
    tunnel/PCIe link), so the wire ships ~a dozen bytes per record — the
    tree analog of the string path's width-coded wire profiles.

    - ``cols`` (R, 3) u8: kind | meta<<4 (meta bit 0 = nested, bit 1 =
      first-record-of-op), field_local, type_local
    - ``ids`` (R, 3) u16/u32: node/parent/after batch-local 1-based
      indices (u32 when the batch id table outgrows u16)
    - ``vals`` (R,) u16/u32: value batch-local index
    - ``row`` (R,) u16 / ``pos`` (R,) u8 or u16: dense scatter
      coordinates; ``pos == o`` (out of range) drops the record (R is
      pow2-padded)
    - ``base`` (D,) i32: each doc's FIRST op seq this batch (per-doc op
      seqs are consecutive within a batch, so per-record seq = base +
      running count of first-of-op bits − 1)
    - ``*_map`` i32: batch-local index → global interner handle
    """
    i32 = jnp.int32
    kind = (cols[:, 0] & 0xF).astype(i32)
    meta = (cols[:, 0] >> 4).astype(i32)
    field = f_map[cols[:, 1].astype(i32)]
    type_ = t_map[cols[:, 2].astype(i32)]
    node = id_map[ids[:, 0].astype(i32)]
    parent = id_map[ids[:, 1].astype(i32)]
    after = id_map[ids[:, 2].astype(i32)]
    value = v_map[vals.astype(i32)]
    d = state.node_id.shape[0]
    r, p = row.astype(i32), pos.astype(i32)
    stacked = jnp.stack([kind, node, parent, after, field, value, type_,
                         meta & 1], axis=0)              # (8, R)
    dense = jnp.zeros((8, d, o), i32).at[:, r, p].set(stacked,
                                                      mode="drop")
    first = jnp.zeros((d, o), i32).at[r, p].set((meta >> 1) & 1,
                                                mode="drop")
    seq = base[:, None] + jnp.cumsum(first, axis=1) - 1
    return apply_tree_batch(state, dense[0], dense[1], dense[2], dense[3],
                            dense[4], dense[5], dense[6], seq, dense[7])


apply_tree_wire_jit = jax.jit(apply_tree_wire, donate_argnums=0,
                              static_argnames=("o",))


@jax.jit
def gather_tree_rows_jit(state: TreeState, rows):
    """Fused device gather of selected doc rows (incremental summary)."""
    return tuple(getattr(state, k)[rows] for k in _TREE_PLANES) + \
        (state.overflow[rows],)


@functools.partial(jax.jit, donate_argnums=0)
def write_tree_rows_jit(state: TreeState, rows, *planes_and_overflow):
    """Overwrite selected doc rows (delta restore; duplicate padding
    rows scatter identical values — a no-op)."""
    updates = {k: getattr(state, k).at[rows].set(planes_and_overflow[i])
               for i, k in enumerate(_TREE_PLANES)}
    return TreeState(**updates,
                     overflow=state.overflow.at[rows].set(
                         planes_and_overflow[-1]))


def tree_state_digest(state: TreeState) -> jax.Array:
    """Per-doc structural digest, invariant to slot layout: mixes each live
    node's (id, parent, field, prev, value, type) — prev encodes sibling
    order, so equal digests mean equal trees."""
    live = state.node_id != 0
    mix = (state.node_id * 1000003 + state.parent * 8191 +
           state.field * 131071 + state.prev_sib * 524287 +
           state.value * 8209 + state.type_ * 127)
    return jnp.sum(jnp.where(live, mix, 0), axis=1) + \
        jnp.sum(live.astype(jnp.int32), axis=1)
