"""Host facade for mega-docs: very long documents sharded across the mesh.

Mirrors ``TensorStringStore`` (payload interning, client indexing, text and
property reads — translation shared via ``StringOpInterner``) for documents
whose segment axis is distributed over the device mesh by
``megadoc_kernel`` — the framework's sequence/context-parallel serving
path. The host orchestrates the distributed zamboni: batches are applied in
windows sized so a shard below the rebalance threshold can never overflow
within one window, with a preemptive rebalance check between windows
(overflow means dropped ops and an oracle rebuild).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from .megadoc_kernel import (
    apply_megadoc_batch, compact_megadoc, create_megadoc_state,
    make_megadoc_mesh, megadoc_digest, rebalance_megadoc, visible_runs,
)
from ..core.constants import NOT_REMOVED
from .schema import OpKind
from .string_store import _TEXT, StringOpInterner


class MegaDocStringStore(StringOpInterner):
    """D mega-docs, each sharded over every device of a 1-D mesh."""

    def __init__(self, n_docs: int, capacity_per_shard: int = 256,
                 mesh=None, rebalance_headroom: float = 0.25):
        self.mesh = mesh if mesh is not None else make_megadoc_mesh()
        self.n_docs = n_docs
        self.capacity_per_shard = capacity_per_shard
        self.rebalance_headroom = rebalance_headroom
        self.state = create_megadoc_state(self.mesh, n_docs,
                                          capacity_per_shard)
        self._init_interner(n_docs, self.state.prop_val.shape[2])
        self._runs_cache = None
        self._runs_state = None

    # --------------------------------------------------------- capacity plane

    def capacity_stats(self) -> dict:
        """Capacity-plane report fragment (ISSUE 19)."""
        from ..utils import capacity as _cap
        return {"host": {"interner": self.interner_host_bytes()},
                "device": {"state": _cap.device_nbytes(self.state)}}

    # ----------------------------------------------------------------- apply

    def apply_messages(self, messages) -> None:
        """messages: iterable of (doc, SequencedDocumentMessage) carrying
        merge-tree op contents; same contract as TensorStringStore."""
        per_doc: Dict[int, list] = {}
        for doc, msg in messages:
            recs = self._records_for(doc, msg)
            if recs:
                per_doc.setdefault(doc, []).extend(recs)
        if not per_doc:
            return
        # Window the op axis so preemptive rebalances interleave: a fresh
        # mega-doc concentrates inserts on one shard, and each op can add
        # up to 2 slots there, so a window of headroom/2 ops can never push
        # a below-threshold shard past its capacity before the next check.
        window = max(1, int(self.capacity_per_shard *
                            self.rebalance_headroom) // 2)
        widest = max(len(v) for v in per_doc.values())
        off = 0
        while off < widest:
            chunk = {d: recs[off:off + window]
                     for d, recs in per_doc.items() if len(recs) > off}
            self._maybe_rebalance()
            self._apply_chunk(chunk)
            off += window

    def _apply_chunk(self, per_doc: Dict[int, list]) -> None:
        import jax.numpy as jnp
        widest = max(len(v) for v in per_doc.values())
        o = 8
        while o < widest:
            o *= 2
        planes = np.zeros((7, self.n_docs, o), np.int32)
        planes[0, :, :] = int(OpKind.NOOP)
        for doc, recs in per_doc.items():
            for j, rec in enumerate(recs):
                planes[:, doc, j] = rec
        self.state = apply_megadoc_batch(
            self.mesh, self.state, *(jnp.asarray(planes[i])
                                     for i in range(7)))

    def _maybe_rebalance(self) -> None:
        """Preemptive distributed zamboni: spread slots when any shard is
        within ``rebalance_headroom`` of its capacity. Overflowed state is
        left untouched (sticky flag preserved for the oracle-drain path)."""
        if np.asarray(self.state.overflow).any():
            return
        counts = np.asarray(self.state.count)
        threshold = self.capacity_per_shard * (1 - self.rebalance_headroom)
        if counts.max() > threshold:
            self.state = rebalance_megadoc(self.mesh, self.state)

    def compact(self, min_seq) -> None:
        ms = np.full((self.n_docs,), int(min_seq), np.int32) \
            if np.isscalar(min_seq) else np.asarray(min_seq, np.int32)
        self.state = compact_megadoc(self.mesh, self.state, ms)

    # ----------------------------------------------------------------- reads

    def _runs(self):
        """visible_runs pulled device→host once per state version (the
        state object is replaced by apply/compact/rebalance)."""
        if self._runs_state is not self.state:
            self._runs_cache = visible_runs(self.state)
            self._runs_state = self.state
        return self._runs_cache

    def read_text(self, doc: int) -> str:
        parts = []
        for op, off, ln, _props in self._runs()[doc]:
            kind, text = self._payloads[op]
            if kind == _TEXT:
                parts.append(text[off:off + ln])
        return "".join(parts)

    def visible_length(self, doc: int) -> int:
        return sum(ln for _op, _off, ln, _p in self._runs()[doc])

    def seq_at(self, doc: int, pos: int) -> int:
        """Insert seq of the slot holding visible position ``pos`` — the
        attribution key, walked shard-major over the sharded planes (same
        contract as TensorStringStore.seq_at)."""
        st = self.state
        count = np.asarray(st.count)
        rem = np.asarray(st.removed_seq)
        ln = np.asarray(st.length)
        sq = np.asarray(st.seq)
        n_shards = count.shape[1]
        s_local = ln.shape[1] // n_shards
        at = 0
        for s in range(n_shards):
            lo = s * s_local
            for i in range(lo, lo + count[doc, s]):
                if rem[doc, i] != NOT_REMOVED:
                    continue
                if at <= pos < at + ln[doc, i]:
                    return int(sq[doc, i])
                at += ln[doc, i]
        raise IndexError(f"doc {doc}: position {pos} beyond length {at}")

    def get_properties(self, doc: int, pos: int) -> dict:
        """Properties of the character at visible position pos."""
        at = 0
        for _op, _off, ln, props in self._runs()[doc]:
            if at <= pos < at + ln:
                return {key: self._prop_values.value(int(props[plane]))
                        for key, plane in self._prop_planes.items()
                        if props[plane] != 0}
            at += ln
        raise IndexError(f"doc {doc}: position {pos} beyond length {at}")

    # ------------------------------------------------- overflow recovery

    def adopt_doc(self, row: int, tmp) -> "MegaDocStringStore":
        """Adopt a rebuilt single-doc flat store's state into mega-doc
        ``row`` — the re-upload step of the overflow escape hatch: the
        compacted slots are distributed evenly across shards, payloads and
        props re-intern into this store's tables, the client map transfers
        wholesale. Rare path: goes through a full snapshot→modify→restore
        round trip. Returns the NEW store (caller replaces its reference)."""
        from ..core.constants import NOT_REMOVED
        n = int(np.asarray(tmp.state.count[0]))
        n_shards = self.mesh.devices.size
        S = self.capacity_per_shard
        if n > n_shards * S:
            raise ValueError(
                f"rebuilt doc needs {n} slots > mega capacity "
                f"{n_shards}×{S}; graduate it instead")
        # intern into self's tables FIRST, then snapshot (captures them)
        hop = self.remap_payload_handles(
            tmp, np.asarray(tmp.state.handle_op[0][:n]))
        prop = np.zeros((n_shards * S, self.n_props), np.int32)
        if tmp._has_props:
            self._has_props = True
            self.remap_props(tmp, np.asarray(tmp.state.prop_val[0][:n]),
                             prop)
        self._client_idx[row] = dict(tmp._client_idx[0])
        snap = self.snapshot()

        flat = {k: np.asarray(getattr(tmp.state, k)[0][:n])
                for k in ("seq", "client", "removed_seq", "removers",
                          "length", "handle_off")}
        flat["handle_op"] = hop
        quota = -(-n // n_shards)  # even spread (ceil)
        counts = np.zeros(n_shards, np.int32)
        for k, arr in snap["planes"].items():
            if k == "prop_val":
                continue
            fill = NOT_REMOVED if k == "removed_seq" else 0
            rowvals = np.full(n_shards * S, fill, np.int32)
            for s in range(n_shards):
                chunk = flat[k][s * quota:(s + 1) * quota]
                rowvals[s * S:s * S + len(chunk)] = chunk
                counts[s] = len(chunk)
            arr[row] = rowvals
        pv = snap["planes"]["prop_val"]
        pv[row] = 0
        for s in range(n_shards):
            chunk = prop[s * quota:(s + 1) * quota]
            pv[row, s * S:s * S + len(chunk), :chunk.shape[1]] = chunk
        snap["count"][row] = counts
        snap["overflow"][row] = 0
        return MegaDocStringStore.restore(snap, mesh=self.mesh)

    def overflowed(self) -> np.ndarray:
        return np.asarray(self.state.overflow)

    def digests(self) -> np.ndarray:
        return np.asarray(megadoc_digest(self.mesh, self.state))

    def slot_usage(self) -> np.ndarray:
        """(D, n_shards) active slot counts."""
        return np.asarray(self.state.count)

    # ----------------------------------------------------- snapshot / resume

    def snapshot(self) -> dict:
        """Device→host gather of the sharded planes plus interning tables
        (same recovery contract as TensorStringStore: restore + log-tail
        replay through the same kernels)."""
        st = self.state
        return {
            "planes": {k: np.asarray(getattr(st, k)).copy()
                       for k in self.SNAP_PLANES},
            "count": np.asarray(st.count).copy(),
            "overflow": np.asarray(st.overflow).copy(),
            "capacity_per_shard": self.capacity_per_shard,
            "n_shards": self.mesh.devices.size,
            "rebalance_headroom": self.rebalance_headroom,
            "payloads": list(self._payloads),
            "client_idx": [dict(m) for m in self._client_idx],
            "prop_planes": dict(self._prop_planes),
            "prop_values": self._prop_values.export(),
            "has_props": self._has_props,
        }

    @classmethod
    def restore(cls, snap: dict, mesh=None) -> "MegaDocStringStore":
        """Rebuild on a mesh with the same device count (shard-local slot
        runs re-upload exactly; a different-size mesh needs a rebalance
        pass, not supported here)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from .megadoc_kernel import STATE_SPECS
        from .merge_tree_kernel import StringState
        n_docs = snap["count"].shape[0]
        # skip __init__'s device allocation: the snapshot fully replaces it
        store = cls.__new__(cls)
        store.mesh = mesh if mesh is not None else make_megadoc_mesh()
        if store.mesh.devices.size != snap["n_shards"]:
            raise ValueError(
                f"snapshot taken on {snap['n_shards']} shards; mesh has "
                f"{store.mesh.devices.size}")
        store.n_docs = n_docs
        store.capacity_per_shard = snap["capacity_per_shard"]
        store.rebalance_headroom = snap["rebalance_headroom"]
        store.n_props = snap["planes"]["prop_val"].shape[2]
        store._runs_cache = None
        store._runs_state = None
        arrays = dict(snap["planes"], count=snap["count"],
                      overflow=snap["overflow"])
        store.state = StringState(**{
            k: jax.device_put(jnp.asarray(arrays[k]),
                              NamedSharding(store.mesh, STATE_SPECS[k]))
            for k in STATE_SPECS
        })
        store._payloads = [tuple(p) for p in snap["payloads"]]
        store._payload_chars = sum(len(p[1]) for p in store._payloads)
        store._client_idx = [dict(m) for m in snap["client_idx"]]
        store._prop_planes = dict(snap["prop_planes"])
        from .schema import ValueInterner
        store._prop_values = ValueInterner.restore(snap["prop_values"])
        store._has_props = snap["has_props"]
        return store
