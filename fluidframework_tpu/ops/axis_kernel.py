"""Device permutation axes for SharedMatrix serving: merge + resolve.

Reference counterpart: ``@fluidframework/matrix`` PermutationVector — a
MergeTree whose "text" is the row/col key space (SURVEY.md §2.4). The
serving engine previously walked host MergeTree observers per op; here
the axis state IS the batched merge-tree kernel state (one row per
(doc, axis)), and position→key resolution happens INSIDE the same scan
that applies the axis mutations: a ``RESOLVE`` op computes, at its own
(ref_seq, client) perspective, the run handle and within-run offset of
the slot containing a position — without mutating state — and the scan
emits those as per-op outputs. One device dispatch applies a whole
window of axis inserts/removes AND resolves every setCell in it.

Key identity: an inserted run interns (mixed opKey, key_offset) to a
run handle (``handle_op``); ``handle_off`` accumulates across splits,
so a resolved (run, handle_off + within) maps host-side to exactly the
oracle's ``(seg.handle[0], seg.handle[1] + off)`` key tuple
(``models/shared_matrix.py`` ``_Axis.resolve``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.constants import NOT_REMOVED
from .merge_tree_kernel import (
    MAX_CLIENTS, StringState, _insert_one, _iota, _prefix, _range_one,
    _visible,
)
from .schema import OpKind

_PLANES = ("seq", "client", "removed_seq", "removers", "length",
           "handle_op", "handle_off")


def _axis_state_dict(state: StringState):
    return {k: getattr(state, k) for k in _PLANES} | {
        "count": state.count, "overflow": state.overflow}


def _resolve_one(s, pos, client_idx, ref_seq):
    """(run handle, run offset) of the slot containing perspective
    position ``pos`` — (-1, -1) when out of range. One-hot sums instead
    of gathers (same rationale as the merge kernel)."""
    vis = _visible(s, ref_seq, client_idx)
    pre, end = _prefix(s, vis)
    inside = vis & (pre <= pos) & (pos < end)
    has = jnp.any(inside)
    hop = jnp.sum(jnp.where(inside, s["handle_op"], 0))
    base = jnp.sum(jnp.where(inside, s["handle_off"], 0))
    preo = jnp.sum(jnp.where(inside, pre, 0))
    return (jnp.where(has, hop, -1),
            jnp.where(has, base + pos - preo, -1))


def apply_axis_batch(state: StringState, kind, a0, a1, a2, seq, client,
                     ref_seq):
    """Apply a dense (D, O) batch of axis ops; returns (state, res_run,
    res_off) where the latter two are (D, O) RESOLVE outputs (-1 at
    non-resolve slots and out-of-range resolves).

    STR_INSERT: a0=pos, a1=count, a2=run handle. STR_REMOVE: a0=start,
    a1=end. AXIS_RESOLVE: a0=pos (emits output, mutates nothing). An
    insert whose position exceeds its perspective's visible length is
    DROPPED (the oracle raises and the engine drops — appending would
    diverge)."""

    def step(carry, op):
        k, p0, p1, p2, sq, cl, rs = op
        ins = jax.vmap(functools.partial(_insert_one, with_props=False)
                       )(carry, p0, p1, p2, sq, cl, rs)
        rng = jax.vmap(functools.partial(_range_one, with_props=False)
                       )(carry, k, p0, p1, p2, sq, cl, rs)
        res_h, res_o = jax.vmap(_resolve_one)(carry, p0, cl, rs)

        def vis_len(s, cl_, rs_):
            vis = _visible(s, rs_, cl_)
            return jnp.sum(jnp.where(vis, s["length"], 0))

        total = jax.vmap(vis_len)(carry, cl, rs)
        ins_ok = p0 <= total

        def pick(key):
            tail = (1,) * (carry[key].ndim - 1)
            is_ins = ((k == OpKind.STR_INSERT) & ins_ok).reshape(
                (-1,) + tail)
            is_rng = (k == OpKind.STR_REMOVE).reshape((-1,) + tail)
            return jnp.where(is_ins, ins[key],
                             jnp.where(is_rng, rng[key], carry[key]))

        out = {key: pick(key) for key in carry}
        is_res = k == OpKind.AXIS_RESOLVE
        y = (jnp.where(is_res, res_h, -1), jnp.where(is_res, res_o, -1))
        return out, y

    sd = _axis_state_dict(state)
    pv = state.prop_val  # threads through untouched (axes carry no props)
    ops = (kind.T, a0.T, a1.T, a2.T, seq.T, client.T, ref_seq.T)
    out, (ys_h, ys_o) = jax.lax.scan(step, sd, ops)
    out["prop_val"] = pv
    return StringState(**out), ys_h.T, ys_o.T


apply_axis_batch_jit = jax.jit(apply_axis_batch, donate_argnums=0)


@jax.jit
def resolve_axis_positions(state: StringState, pos, client, ref_seq):
    """Resolve a (D, O) batch of positions against the CURRENT axis state
    — no interleaved mutations, so every resolve sees the same planes and
    the whole batch is a pure vmap (elementwise, no sequential scan): the
    fast path for resolve-only windows (columnar setCell ingest, reads).
    Returns (run, off) (D, O) planes, -1 where out of range."""
    sd = {k: getattr(state, k) for k in _PLANES} | {
        "count": state.count, "overflow": state.overflow}

    def per_doc(s, p_row, cl_row, rs_row):
        return jax.vmap(lambda p, c, r: _resolve_one(s, p, c, r))(
            p_row, cl_row, rs_row)

    rh, ro = jax.vmap(per_doc)(sd, pos, client, ref_seq)
    return rh, ro


@jax.jit
def axis_visible_lengths(state: StringState):
    """(D,) latest-view visible length per axis row (dims read)."""
    S = state.seq.shape[1]
    active = jnp.arange(S)[None, :] < state.count[:, None]
    live = active & (state.removed_seq == NOT_REMOVED)
    return jnp.sum(jnp.where(live, state.length, 0), axis=1)


class TensorAxisStore:
    """Host facade: 2 permutation axes per matrix doc (rows at
    ``2·doc``, cols at ``2·doc + 1``), resident as one StringState.
    Run identities intern (mixed opKey, key_offset) → int32 handles;
    per-axis-row client interning feeds the remover bitmask."""

    def __init__(self, n_docs: int, capacity: int = 256, mesh=None):
        """``mesh``: a 1-D ``docs`` device mesh shards the axis rows by
        doc block (a doc's row+col axes stay on one chip); the axis scan
        runs as a collective-free shard_map of the same kernel."""
        self.n_docs = n_docs
        self.capacity = capacity
        self.mesh = mesh
        self.state = StringState.create(2 * n_docs, capacity, n_props=1)
        if mesh is not None:
            from ..parallel.sharded import shard_axis_store_state
            self.state = shard_axis_store_state(self.state, mesh)
        self._runs: List[Tuple[int, int]] = [(0, 0)]  # run 0 reserved
        self._run_ids: Dict[Tuple[int, int], int] = {}
        self._runs_np = None  # cached columnar view of _runs
        self._client_idx: List[Dict[int, int]] = [
            dict() for _ in range(2 * n_docs)]

    def run_handle(self, mixed: int, key_offset: int) -> int:
        k = (int(mixed), int(key_offset))
        if k not in self._run_ids:
            self._run_ids[k] = len(self._runs)
            self._runs.append(k)
        return self._run_ids[k]

    def run_key(self, handle: int, off: int) -> Tuple[int, int]:
        mixed, base = self._runs[handle]
        return (mixed, base + off)

    def runs_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """The run table as (mixed, base) int64 columns — re-materialized
        only when the table has grown, so a whole resolved-key stream
        turns into two gathers instead of per-op ``run_key`` calls."""
        cache = self._runs_np
        if cache is None or len(cache[0]) != len(self._runs):
            arr = np.asarray(self._runs, np.int64).reshape(-1, 2)
            cache = self._runs_np = (np.ascontiguousarray(arr[:, 0]),
                                     np.ascontiguousarray(arr[:, 1]))
        return cache

    def client(self, axis_row: int, client_id: int) -> int:
        m = self._client_idx[axis_row]
        if client_id not in m:
            if len(m) >= MAX_CLIENTS:
                raise KeyError(f"axis {axis_row}: client capacity")
            m[client_id] = len(m)
        return m[client_id]

    def apply(self, planes: dict) -> Tuple[np.ndarray, np.ndarray]:
        """One device dispatch; returns host (D2, O) resolve outputs
        (the flush's single device→host read). A resolve-only window
        skips the sequential scan entirely (pure vmap — see
        ``resolve_axis_positions``)."""
        kind = np.asarray(planes["kind"])
        if self.mesh is None and np.isin(
                kind, (int(OpKind.AXIS_RESOLVE),
                       int(OpKind.NOOP))).all():
            rh, ro = resolve_axis_positions(
                self.state, jnp.asarray(planes["a0"]),
                jnp.asarray(planes["client"]),
                jnp.asarray(planes["ref_seq"]))
            is_res = kind == int(OpKind.AXIS_RESOLVE)
            return (np.where(is_res, np.asarray(rh), -1),
                    np.where(is_res, np.asarray(ro), -1))
        if self.mesh is not None:
            from ..parallel.sharded import sharded_axis_apply
            self.state, rh, ro = sharded_axis_apply(self.mesh)(
                self.state,
                tuple(jnp.asarray(planes[k]) for k in
                      ("kind", "a0", "a1", "a2", "seq", "client",
                       "ref_seq")))
            return np.asarray(rh), np.asarray(ro)
        self.state, rh, ro = apply_axis_batch_jit(
            self.state,
            *(jnp.asarray(planes[k]) for k in
              ("kind", "a0", "a1", "a2", "seq", "client", "ref_seq")))
        return np.asarray(rh), np.asarray(ro)

    def resolve_async(self, planes: dict):
        """Mutation-free position resolves returned as DEVICE arrays,
        with the host copy started asynchronously — the caller harvests
        them later, so the ingest path never blocks on a device round
        trip (the matrix engine's resolve pipelining)."""
        if self.mesh is not None:
            from ..parallel.sharded import sharded_axis_apply
            st, rh, ro = sharded_axis_apply(self.mesh)(
                self.state,
                tuple(jnp.asarray(planes[k]) for k in
                      ("kind", "a0", "a1", "a2", "seq", "client",
                       "ref_seq")))
            self.state = st   # resolve-only: content unchanged
        else:
            rh, ro = resolve_axis_positions(
                self.state, jnp.asarray(planes["a0"]),
                jnp.asarray(planes["client"]),
                jnp.asarray(planes["ref_seq"]))
        for x in (rh, ro):
            try:
                x.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass
        return rh, ro

    def visible_lengths(self) -> np.ndarray:
        return np.asarray(axis_visible_lengths(self.state))

    def compact(self, min_seq: np.ndarray) -> None:
        if self.mesh is not None:
            from ..parallel.sharded import sharded_compact
            self.state = sharded_compact(self.mesh, with_props=False)(
                self.state, jnp.asarray(min_seq))
            return
        from .merge_tree_kernel import compact_string_state_jit
        self.state = compact_string_state_jit(
            self.state, jnp.asarray(min_seq), with_props=False)

    def overflowed(self) -> np.ndarray:
        return np.asarray(self.state.overflow)

    # ----------------------------------------------------- snapshot/resume

    def snapshot(self) -> dict:
        st = self.state
        n = max(int(np.asarray(st.count).max()), 1)
        return {
            "planes": {k: np.asarray(getattr(st, k))[:, :n].copy()
                       for k in _PLANES},
            "count": np.asarray(st.count).copy(),
            "overflow": np.asarray(st.overflow).copy(),
            "capacity": self.capacity,
            "runs": [list(r) for r in self._runs],
            "client_idx": [dict(m) for m in self._client_idx],
        }

    def snapshot_rows(self, axis_rows, runs_base: int) -> dict:
        """Incremental snapshot of the given AXIS rows (2 per dirty doc):
        one fused device gather, plus the append-only run-table delta
        since ``runs_base``; clean axis rows ride by reference to the
        base summary."""
        from .schema import pad_rows_pow2
        from .string_store import _gather_rows_jit
        rows = np.ascontiguousarray(axis_rows, np.int32)
        if len(rows):
            rows_p, _p2, n = pad_rows_pow2(rows)
            g = [np.asarray(x)[:n] for x in
                 _gather_rows_jit(self.state, jnp.asarray(rows_p))]
            w = max(int(g[8].max()), 1)
            planes = {k: g[i][:, :w].copy()
                      for i, k in enumerate(_PLANES)}
            counts, overflow = g[8].copy(), g[9].copy()
        else:
            planes = {k: np.zeros((0, 1), np.int32) for k in _PLANES}
            counts = overflow = np.zeros((0,), np.int32)
        return {
            "rows": rows, "planes": planes, "count": counts,
            "overflow": overflow,
            "runs_delta": [list(r) for r in self._runs[runs_base:]],
            "client_idx": {int(r): dict(self._client_idx[int(r)])
                           for r in rows},
        }

    def apply_row_snapshot(self, delta: dict) -> None:
        """Fold one ``snapshot_rows`` delta into this (restored-base)
        store: extend the run table, replace the rows' client maps,
        overwrite the rows' planes in one scatter."""
        from .string_store import _write_rows_jit
        for r in delta["runs_delta"]:
            k = (int(r[0]), int(r[1]))
            self._run_ids[k] = len(self._runs)
            self._runs.append(k)
        rows = np.asarray(delta["rows"], np.int32)
        if not len(rows):
            return
        for r, m in delta["client_idx"].items():
            self._client_idx[int(r)] = {int(c): v for c, v in m.items()}
        from .schema import bucket_rows, pad_rows_pow2
        w = delta["planes"]["seq"].shape[1]
        rows_p, p2, n = pad_rows_pow2(rows)

        def bucket(a):
            return jnp.asarray(bucket_rows(a, p2, n))

        def pad(k):
            fill = NOT_REMOVED if k == "removed_seq" else 0
            out = np.full((p2, self.capacity), fill, np.int32)
            out[:n, :w] = delta["planes"][k]
            out[n:] = out[:1]
            return jnp.asarray(out)

        prop = jnp.zeros((p2, self.capacity, 1), jnp.int32)
        self.state = _write_rows_jit(
            self.state, jnp.asarray(rows_p),
            *(pad(k) for k in _PLANES), prop,
            bucket(delta["count"]), bucket(delta["overflow"]))

    @classmethod
    def restore(cls, snap: dict, mesh=None) -> "TensorAxisStore":
        store = cls.__new__(cls)
        store.n_docs = snap["count"].shape[0] // 2
        store.capacity = snap["capacity"]
        store.mesh = mesh
        cap = snap["capacity"]
        full = {}
        for k in _PLANES:
            small = np.asarray(snap["planes"][k])
            fill = NOT_REMOVED if k == "removed_seq" else 0
            plane = np.full((snap["count"].shape[0], cap), fill, np.int32)
            plane[:, :small.shape[1]] = small
            full[k] = jnp.asarray(plane)
        store.state = StringState(
            **full,
            prop_val=jnp.zeros((snap["count"].shape[0], cap, 1), jnp.int32),
            count=jnp.asarray(snap["count"]),
            overflow=jnp.asarray(snap["overflow"]))
        if mesh is not None:
            from ..parallel.sharded import shard_axis_store_state
            store.state = shard_axis_store_state(store.state, mesh)
        store._runs = [tuple(r) for r in snap["runs"]]
        store._run_ids = {r: i for i, r in enumerate(store._runs) if i}
        store._runs_np = None
        store._client_idx = [dict(m) for m in snap["client_idx"]]
        return store
