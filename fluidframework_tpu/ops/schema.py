"""Packed fixed-width op records: the device-side wire format.

Reference counterpart: ``ISequencedDocumentMessage`` + per-DDS op contents
(``@fluidframework/protocol-definitions``, merge-tree ``IMergeTreeOp``; mount
empty — SURVEY.md §7.2). The reference ships ops as JSON; a TPU cannot chase
JSON, so ops become struct-of-arrays int32 records:

    doc        — document index within the resident batch (the DP axis)
    client     — sequenced client id
    client_seq — per-client monotone counter (dedupe key at the sequencer)
    ref_seq    — referenceSequenceNumber (the perspective for position resolve)
    seq        — global per-doc sequence number (stamped by the sequencer)
    kind       — OpKind below
    a0/a1/a2   — op-kind-specific args (positions, lengths, key ids, handles)

Variable-length payloads (text bytes, JSON values) never reach the device: they
live in a host-side payload table, and records carry integer handles + lengths.
Position math — the actual hot path — only needs lengths.

Device-resident state is *acked-only*: every op in a batch has a real ``seq``.
Optimistic local state, acks and rebase are a host/client concern
(``fluidframework_tpu.models``); the device is the replica/server merge engine.
"""

from __future__ import annotations

import dataclasses
import enum
import json

import numpy as np


class OpKind(enum.IntEnum):
    # merge-tree / SharedString ops (reference: IMergeTreeOp types)
    STR_INSERT = 0    # a0=pos, a1=len, a2=payload handle
    STR_REMOVE = 1    # a0=start, a1=end
    STR_ANNOTATE = 2  # a0=start, a1=end, a2=props handle
    # map ops (reference: @fluidframework/map IDirectoryOperation)
    MAP_SET = 3       # a0=key id, a1=value handle
    MAP_DELETE = 4    # a0=key id
    MAP_CLEAR = 5
    # matrix ops (reference: @fluidframework/matrix)
    MAT_SET_CELL = 6  # a0=row handle, a1=col handle, a2=value handle
    MAT_INSERT_ROWS = 7  # a0=pos, a1=count
    MAT_INSERT_COLS = 8
    MAT_REMOVE_ROWS = 9  # a0=start, a1=count
    MAT_REMOVE_COLS = 10
    # counter
    COUNTER_INCREMENT = 11  # a0=delta
    NOOP = 12         # heartbeat: advances client ref_seq for MSN only
    AXIS_RESOLVE = 13  # matrix axis query: a0=pos → (run, off), no mutation


N_OP_FIELDS = 9
OP_FIELDS = (
    "doc", "client", "client_seq", "ref_seq", "seq", "kind", "a0", "a1", "a2",
)

# Per-segment state columns for the tensorized MergeTree (ops/merge_tree_kernel).
SEGMENT_FIELDS = (
    "seq",            # insert seq (SEQ_UNIVERSAL for summary-loaded)
    "client",         # inserting client
    "removed_seq",    # NOT_REMOVED if live
    "length",         # character length
    "handle",         # payload handle: (op id << 8 | split ordinal) — host text table
    "active",         # slot in use (0/1)
)


@dataclasses.dataclass
class OpBatch:
    """A batch of sequenced ops as struct-of-arrays, shape (n_ops,) each.

    Ops in a batch are globally ordered by ``seq`` (ascending) and may target
    many docs; per-doc order is a subsequence of the batch order, preserving
    the total order the sequencer assigned.
    """

    doc: np.ndarray
    client: np.ndarray
    client_seq: np.ndarray
    ref_seq: np.ndarray
    seq: np.ndarray
    kind: np.ndarray
    a0: np.ndarray
    a1: np.ndarray
    a2: np.ndarray

    def __len__(self) -> int:
        return int(self.doc.shape[0])

    @staticmethod
    def empty(n: int) -> "OpBatch":
        z = lambda: np.zeros((n,), dtype=np.int32)
        return OpBatch(z(), z(), z(), z(), z(), z(), z(), z(), z())

    @staticmethod
    def from_records(records) -> "OpBatch":
        """records: iterable of (doc, client, client_seq, ref_seq, seq, kind, a0, a1, a2)."""
        arr = np.asarray(list(records), dtype=np.int32).reshape(-1, N_OP_FIELDS)
        return OpBatch(*(np.ascontiguousarray(arr[:, i]) for i in range(N_OP_FIELDS)))

    def as_stacked(self) -> np.ndarray:
        """(n_ops, N_OP_FIELDS) int32 view for device transfer as one array."""
        return np.stack(
            [getattr(self, f) for f in OP_FIELDS], axis=1
        ).astype(np.int32)

    @staticmethod
    def from_stacked(arr: np.ndarray) -> "OpBatch":
        return OpBatch(*(np.ascontiguousarray(arr[:, i]) for i in range(N_OP_FIELDS)))


def pad_rows_pow2(rows):
    """Pow2-pad a dirty-row list for the incremental-summary gather/
    scatter jits (one compiled program per BUCKET, not per distinct row
    count). Padding repeats row 0 — a duplicate gather is discarded, a
    duplicate scatter writes identical values (a no-op). Returns
    (rows_padded, p2, n)."""
    import numpy as np
    rows = np.ascontiguousarray(rows, np.int32)
    n = len(rows)
    p2 = 1 << (n - 1).bit_length() if n else 1
    if p2 > n:
        rows = np.concatenate([rows, np.full(p2 - n, rows[0], np.int32)])
    return rows, p2, n


def bucket_rows(a, p2: int, n: int):
    """Pad a per-row array to the pow2 bucket by repeating row 0's
    entry (the scatter-side counterpart of ``pad_rows_pow2``)."""
    import numpy as np
    a = np.asarray(a, np.int32)
    if p2 > n:
        a = np.concatenate([a, np.repeat(a[:1], p2 - n, axis=0)])
    return a


class ValueInterner:
    """JSON value ↔ int32 handle interning shared by the device stores
    (map/matrix): handle 0 is reserved for "no value"; equal values (by
    canonical JSON encoding) share one handle."""

    def __init__(self):
        self._values: list = [None]
        self._ids: dict = {}

    def handle(self, value) -> int:
        enc = json.dumps(value, sort_keys=True)
        if enc not in self._ids:
            self._ids[enc] = len(self._values)
            self._values.append(value)
        return self._ids[enc]

    def bulk(self, items) -> list:
        """Handles for a whole value table at once (columnar ingest)."""
        ids = self._ids
        values = self._values
        get = ids.get
        dumps = json.dumps
        out = []
        append = out.append
        for v in items:
            enc = dumps(v, sort_keys=True)
            h = get(enc)
            if h is None:
                h = len(values)
                ids[enc] = h
                values.append(v)
            append(h)
        return out

    def bulk_ints(self, items) -> list:
        """``bulk`` fast lane for homogeneous Python-int columns: the
        canonical JSON of an int IS ``repr(int)``, so the dumps machinery
        drops out (callers must exclude ``bool`` — ``True`` and ``1``
        canonicalize differently)."""
        ids = self._ids
        values = self._values
        get = ids.get
        out = []
        append = out.append
        for v in items:
            enc = repr(v)
            h = get(enc)
            if h is None:
                h = len(values)
                ids[enc] = h
                values.append(v)
            append(h)
        return out

    def value(self, handle: int):
        return self._values[handle]

    def __len__(self) -> int:
        return len(self._values)

    def export(self) -> list:
        """Values in handle order (element 0 is the reserved None)."""
        return list(self._values)

    def export_from(self, base: int) -> list:
        """Values appended since ``base`` (incremental-summary delta;
        the table is append-only)."""
        return list(self._values[base:])

    def extend_from(self, values: list) -> None:
        """Re-append an ``export_from`` delta (restore path)."""
        for v in values:
            self.handle(v)

    @classmethod
    def restore(cls, values: list) -> "ValueInterner":
        it = cls()
        for v in values[1:]:
            it.handle(v)
        return it
