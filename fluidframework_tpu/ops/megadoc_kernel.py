"""Mega-doc merge: one document's segment axis sharded across the mesh.

This is the framework's sequence/context-parallelism. The reference has no
tensor axes to shard — its analog of "long context" is MergeTree scaling in
document length (SURVEY.md §5.7) — so the TPU-native design shards the
*segment axis* of a very long document across chips, the way ring attention
shards the sequence axis: each device owns a contiguous run of segment
slots, and per-op position resolution becomes a distributed prefix sum
(all-gather of per-shard visible lengths over ICI + local cumsum), after
which exactly one shard applies an insert locally and every shard marks its
clipped slice of a remove. Communication per op is two small all-gathers
((D,) visible totals, then (D, 2) owner flags that depend on the exclusive
prefix) — bandwidth-trivial, latency-bound on ICI.

Reuses the single-shard roll-based helpers from ``merge_tree_kernel`` (the
local apply is identical vector math); only position resolution is
collective. Semantics match the single-device kernel: the content digest of
a mega-doc equals ``string_state_digest`` of the same ops applied to one
unsharded state (tested on the virtual 8-device CPU mesh).

Layout: D mega-docs × S_local slots per device, planes sharded
``P(None, SEG_AXIS)``; ops replicated. The host calls
``rebalance_megadoc`` preemptively (between batches, while shards still
have headroom) to spread slots evenly — the distributed zamboni. A shard
whose slots fill mid-batch sets the per-(doc, shard) sticky overflow flag,
which means ops were DROPPED: that doc must be drained and rebuilt through
the oracle (the same escape hatch as the single-device kernel), not
rebalanced — ``rebalance_megadoc`` refuses overflowed state.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .merge_tree_kernel import (
    _PLANES, StringState, _insert_one, _range_one, _state_dict, _visible,
    compact_string_state,
)
from ..core.constants import NOT_REMOVED
from .schema import OpKind

SEG_AXIS = "seg"
_SPEC = P(None, SEG_AXIS)
# Every plane AND count/overflow shard on the segment axis: count/overflow
# are per-(doc, shard) quantities carried as (D, n_shards) columns globally,
# so inside shard_map each device sees (D, 1) and squeezes to its own (D,).
STATE_SPECS = dict({k: _SPEC for k in _PLANES}, count=_SPEC, overflow=_SPEC,
                   prop_val=P(None, SEG_AXIS, None))


def _narrow(sd):
    """Shard-local (D, 1) count/overflow columns → (D,) vectors."""
    return dict(sd, count=sd["count"][:, 0], overflow=sd["overflow"][:, 0])


def _widen(sd):
    """(D,) shard-local count/overflow → (D, 1) columns for out_specs."""
    return dict(sd, count=sd["count"][:, None],
                overflow=sd["overflow"][:, None])


def make_megadoc_mesh(n_devices: int | None = None) -> Mesh:
    devices = jax.devices()
    n = n_devices or len(devices)
    return Mesh(np.array(devices[:n]).reshape(n), (SEG_AXIS,))


def _shard_step(n_shards: int):
    """Per-shard body: planes (D, S_local) local to this device."""

    def step(sd, op):
        kind, a0, a1, a2, seq, client, ref_seq = op
        idx = jax.lax.axis_index(SEG_AXIS)

        def one(s, k, p0, p1, p2, sq, cl, rs):
            S = s["seq"].shape[0]
            vis = _visible(s, rs, cl)
            pl = jnp.where(vis, s["length"], 0)
            local_vis = jnp.sum(pl)

            # Insert ownership must reproduce the single-device rule (insert
            # at the leftmost ACTIVE slot whose perspective-prefix >= pos,
            # counting invisible concurrent segments): the owner is the shard
            # strictly containing pos inside a visible segment if one exists,
            # else the FIRST shard holding any such candidate slot — trailing
            # invisible concurrent inserts at the boundary belong to the
            # earlier shard, and a later-sequenced insert must land LEFT of
            # them.
            active = jnp.arange(S) < s["count"]
            g_pre = jnp.cumsum(pl) - pl
            totals = jax.lax.all_gather(local_vis, SEG_AXIS)   # (n_shards,)
            ex = jnp.sum(jnp.where(jnp.arange(n_shards) < idx, totals, 0))
            gp = ex + g_pre
            inside_here = jnp.any(vis & (gp < p0) & (p0 < gp + s["length"]))
            cand_here = jnp.any(active & (gp >= p0))
            flags = jax.lax.all_gather(
                jnp.stack([inside_here.astype(jnp.int32),
                           cand_here.astype(jnp.int32)]), SEG_AXIS)  # (n, 2)
            owner = jnp.where(
                jnp.any(flags[:, 0] > 0), jnp.argmax(flags[:, 0]),
                jnp.where(jnp.any(flags[:, 1] > 0), jnp.argmax(flags[:, 1]),
                          n_shards - 1))
            owns = idx == owner
            ins = _insert_one(s, p0 - ex, p1, p2, sq, cl, rs)
            ins = {k2: jnp.where(owns, ins[k2], s[k2]) for k2 in s}

            # ---- remove/annotate: every shard marks its clipped overlap
            l0 = jnp.clip(p0 - ex, 0, local_vis)
            l1 = jnp.clip(p1 - ex, 0, local_vis)
            rng = _range_one(s, k, l0, l1, p2, sq, cl, rs)
            rng = {k2: jnp.where(l1 > l0, rng[k2], s[k2]) for k2 in s}

            is_ins = k == OpKind.STR_INSERT
            is_rng = (k == OpKind.STR_REMOVE) | (k == OpKind.STR_ANNOTATE)
            return {k2: jnp.where(is_ins, ins[k2],
                                  jnp.where(is_rng, rng[k2], s[k2]))
                    for k2 in s}

        return jax.vmap(one)(sd, kind, a0, a1, a2, seq, client, ref_seq), None

    return step


def _megadoc_apply_local(n_shards, sd, kind, a0, a1, a2, seq, client,
                         ref_seq):
    """shard_map body: scan the op axis with collective position resolve."""
    ops = (kind.T, a0.T, a1.T, a2.T, seq.T, client.T, ref_seq.T)
    out, _ = jax.lax.scan(_shard_step(n_shards), sd, ops)
    return out


@functools.lru_cache(maxsize=8)
def _apply_megadoc_fn(mesh: Mesh):
    """Jitted shard_map apply for one mesh — cached so repeated batches hit
    the jit cache instead of re-tracing a fresh shard_map closure."""
    op_spec = P(None, None)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(STATE_SPECS,) + (op_spec,) * 7,
        out_specs=STATE_SPECS)
    def run(sd, *ops):
        return _widen(_megadoc_apply_local(mesh.devices.size, _narrow(sd),
                                           *ops))

    return jax.jit(run)


def apply_megadoc_batch(mesh: Mesh, state: StringState, kind, a0, a1, a2,
                        seq, client, ref_seq) -> StringState:
    """Apply a dense (D, O) sequenced batch to D seg-sharded mega-docs."""
    out = _apply_megadoc_fn(mesh)(_state_dict(state), kind, a0, a1, a2, seq,
                                  client, ref_seq)
    return StringState(**out)


@functools.lru_cache(maxsize=8)
def _digest_fn(mesh: Mesh):
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(_SPEC,) * 6,
        out_specs=P(None))
    def run(seq, removed, length, h_op, h_off, count):
        S = seq.shape[1]
        n = jax.lax.axis_size(SEG_AXIS)
        idx = jax.lax.axis_index(SEG_AXIS)
        active = jnp.arange(S)[None, :] < count[:, :1]
        live = active & (removed == NOT_REMOVED)
        pl = jnp.where(live, length, 0)
        local_tot = jnp.sum(pl, axis=1)                        # (D,)
        totals = jax.lax.all_gather(local_tot, SEG_AXIS, axis=1)  # (D, n)
        ex = jnp.sum(jnp.where(jnp.arange(n)[None, :] < idx, totals, 0),
                     axis=1)                                   # (D,)
        pre = jnp.cumsum(pl, axis=1) - pl + ex[:, None]
        mix = (h_op * 1000003 + (h_off - pre) * 8191) * pl
        part = jnp.sum(jnp.where(live, mix, 0), axis=1) + local_tot
        return jax.lax.psum(part, SEG_AXIS)

    return jax.jit(run)


def megadoc_digest(mesh: Mesh, state: StringState) -> jax.Array:
    """Content digest of each mega-doc, equal to ``string_state_digest`` of
    the same content held unsharded (global visible prefix via collective)."""
    return _digest_fn(mesh)(state.seq, state.removed_seq, state.length,
                            state.handle_op, state.handle_off, state.count)


@functools.lru_cache(maxsize=8)
def _compact_fn(mesh: Mesh):
    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(STATE_SPECS, P(None)), out_specs=STATE_SPECS)
    def run(sd, ms):
        local = StringState(**_narrow(sd))
        return _widen(_state_dict(compact_string_state(local, ms)))

    return jax.jit(run)


def compact_megadoc(mesh: Mesh, state: StringState, min_seq) -> StringState:
    """Distributed zamboni: each shard compacts its own slot run locally.

    Tombstones acked at or below min_seq (D,) are dropped shard-locally with
    the same stable-partition sort as ``compact_string_state`` — no
    communication needed, since slot ownership never crosses shards; only
    the host rebalancer (overflow path) moves segments between shards."""
    out = _compact_fn(mesh)(_state_dict(state),
                            jnp.asarray(min_seq, jnp.int32))
    return StringState(**out)


def rebalance_megadoc(mesh: Mesh, state: StringState) -> StringState:
    """Host-side PREEMPTIVE shard rebalance (call while shards have headroom).

    A fresh mega-doc concentrates content on whichever shard owns the
    insert positions (initially the last), so shards fill unevenly. This
    pulls the planes to host, concatenates each doc's shard-local active
    runs in shard order (= global document order), deals the slots back out
    evenly across shards, and re-uploads with the same shardings.
    Tombstones move with their neighbours: they still govern visibility for
    ops whose ref_seq predates the removal.

    Raises on sticky overflow: a set flag means ops were already dropped
    and the doc's content is unrecoverable from device state — it must be
    drained and rebuilt through the oracle instead (rebalancing would
    silently erase the only evidence of the loss)."""
    if np.asarray(state.overflow).any():
        raise ValueError(
            "mega-doc state has sticky overflow: ops were dropped; drain "
            "the affected docs through the oracle and rebuild — rebalance "
            "cannot recover them")
    n = mesh.devices.size
    S_local = state.seq.shape[1] // n
    keys = _PLANES + ("prop_val",)
    planes = {k: np.asarray(getattr(state, k)) for k in keys}
    count = np.asarray(state.count)                       # (D, n)
    D = count.shape[0]
    new = {k: np.zeros_like(planes[k]) for k in keys}
    new["removed_seq"][:] = NOT_REMOVED
    new_count = np.zeros((D, n), np.int32)
    for d in range(D):
        cat = {k: np.concatenate([
            planes[k][d, s * S_local: s * S_local + count[d, s]]
            for s in range(n)]) for k in keys}
        tot = len(cat["seq"])
        base, extra = divmod(tot, n)
        off = 0
        for s in range(n):
            c = base + (1 if s < extra else 0)
            if c > S_local:
                raise ValueError(f"doc {d}: {tot} live slots exceed "
                                 f"mesh capacity {n * S_local}")
            for k in keys:
                new[k][d, s * S_local: s * S_local + c] = cat[k][off:off + c]
            new_count[d, s] = c
            off += c
    arrays = dict(new, count=new_count,
                  overflow=np.zeros((D, n), np.int32))
    return StringState(**{
        k: jax.device_put(jnp.asarray(arrays[k]),
                          NamedSharding(mesh, STATE_SPECS[k]))
        for k in STATE_SPECS
    })


def create_megadoc_state(mesh: Mesh, n_docs: int,
                         capacity_per_shard: int) -> StringState:
    """(D, n_shards * S_local) planes with count/overflow per (doc, shard)."""
    n = mesh.devices.size
    st = StringState.create(n_docs, n * capacity_per_shard)
    wide = StringState(
        seq=st.seq, client=st.client, removed_seq=st.removed_seq,
        removers=st.removers, length=st.length, handle_op=st.handle_op,
        handle_off=st.handle_off, prop_val=st.prop_val,
        count=jnp.zeros((n_docs, n), jnp.int32),
        overflow=jnp.zeros((n_docs, n), jnp.int32),
    )
    return StringState(**{
        k: jax.device_put(getattr(wide, k),
                          NamedSharding(mesh, STATE_SPECS[k]))
        for k in STATE_SPECS
    })


def visible_runs(state: StringState):
    """Host-side order-SENSITIVE content oracle: per doc, the
    (handle_op, handle_off, length, props) runs of live segments in document
    order, adjacent pieces of the same insert with identical properties
    coalesced so the result is invariant to physical split history. Accepts
    both layouts: single-device state (count shape (D,)) and mega-doc state
    (count shape (D, n_shards), slots shard-major). Unlike the additive
    digest this detects reordered content and lost/misplaced annotations."""
    count = np.asarray(state.count)
    n_shards = 1 if count.ndim == 1 else count.shape[1]
    count = count.reshape(count.shape[0], n_shards)
    planes = {k: np.asarray(getattr(state, k)) for k in
              ("removed_seq", "handle_op", "handle_off", "length")}
    props = np.asarray(state.prop_val)
    D = count.shape[0]
    S_local = planes["length"].shape[1] // n_shards
    docs = []
    for d in range(D):
        runs = []
        for s in range(n_shards):
            lo = s * S_local
            for i in range(lo, lo + count[d, s]):
                if planes["removed_seq"][d, i] != NOT_REMOVED:
                    continue
                op = int(planes["handle_op"][d, i])
                off = int(planes["handle_off"][d, i])
                ln = int(planes["length"][d, i])
                pv = tuple(int(x) for x in props[d, i])
                if runs and runs[-1][0] == op and \
                        runs[-1][1] + runs[-1][2] == off and \
                        runs[-1][3] == pv:
                    runs[-1] = (op, runs[-1][1], runs[-1][2] + ln, pv)
                else:
                    runs.append((op, off, ln, pv))
        docs.append(runs)
    return docs
