"""Batched SharedMatrix cell-merge kernel: sorted sparse cell table on device.

Reference counterpart: ``@fluidframework/matrix`` cell storage
(``SparseArray2D`` + LWW set-cell conflict policy, with the one-way
``switchSetCellPolicy`` flip to first-writer-wins) — SURVEY.md §2.4 (mount
empty). Row/col permutation merges stay on the host's MergeTree-backed axes
(``models.shared_matrix``); what reaches the device is the cell-write hot
path: a stream of (cellId, seq, value) records to merge LWW into the
persistent cell set — BASELINE config #3's 1k×1k concurrent-edit storm.

TPU-first design: scatter-by-cell (the "obvious" layout) measures ~160k
ops/s on this chip because XLA scatter serializes; a multi-operand bitonic
``lax.sort`` of >1M rows runs in ~28 ms. So the state is a **sorted sparse
table** of (cell key, seq, value) and a batch merge is:

    concat(table, batch) → sort by (key, seq) → mark per-key winner →
    demote losers to EMPTY_KEY → sort by key → truncate to capacity

Two sorts, zero gathers/scatters. Empty slots carry ``EMPTY_KEY`` so they
sort to the tail and truncation only ever drops empties (a sticky overflow
flag is set if a live entry would fall off — the host's cue to re-bucket,
same escape hatch as ``StringState``).

Cell identity: the host interns each resolved (rowKey, colKey) identity —
stable across concurrent row/col inserts because identities come from the
permutation trees, not positions — to a dense int32 cell id.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .schema import ValueInterner

EMPTY_KEY = np.int32(2**31 - 1)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MatrixCellState:
    """Device-resident sorted sparse cell table (capacity T rows)."""

    key: jax.Array      # (T,) int32 cell id, EMPTY_KEY in free slots
    seq: jax.Array      # (T,) int32 seq of the winning write
    value: jax.Array    # (T,) int32 payload handle
    count: jax.Array    # ()   int32 live entries
    overflow: jax.Array  # ()  int32 sticky overflow flag

    @staticmethod
    def create(capacity: int) -> "MatrixCellState":
        return MatrixCellState(
            key=jnp.full((capacity,), EMPTY_KEY, jnp.int32),
            seq=jnp.zeros((capacity,), jnp.int32),
            value=jnp.zeros((capacity,), jnp.int32),
            count=jnp.zeros((), jnp.int32),
            overflow=jnp.zeros((), jnp.int32),
        )


def apply_cells_batch(state: MatrixCellState, op_key, op_seq, op_value,
                      fww=False) -> MatrixCellState:
    """Merge a (O,) batch of sequenced set-cell ops into the cell table.

    op_key/op_seq/op_value: (O,) int32; NOOP pads carry EMPTY_KEY. ``fww``
    switches the conflict policy to first-writer-wins (earliest acked seq
    keeps the cell — the reference's ``switchSetCellPolicy``); existing
    table entries still count as earlier writers via their stored seq.
    """
    T = state.key.shape[0]
    keys = jnp.concatenate([state.key, op_key])
    seqs = jnp.concatenate([state.seq, op_seq])
    vals = jnp.concatenate([state.value, op_value])

    keys, seqs, vals = jax.lax.sort([keys, seqs, vals], num_keys=2,
                                    is_stable=False)
    nxt_same = jnp.concatenate(
        [keys[1:] == keys[:-1], jnp.zeros((1,), bool)])
    prv_same = jnp.concatenate(
        [jnp.zeros((1,), bool), keys[1:] == keys[:-1]])
    win = jnp.where(fww, ~prv_same, ~nxt_same) & (keys != EMPTY_KEY)

    keys = jnp.where(win, keys, EMPTY_KEY)
    keys, seqs, vals = jax.lax.sort([keys, seqs, vals], num_keys=1,
                                    is_stable=False)
    live = jnp.sum((keys != EMPTY_KEY).astype(jnp.int32))
    return MatrixCellState(
        key=keys[:T], seq=seqs[:T], value=vals[:T],
        count=jnp.minimum(live, T),
        overflow=jnp.where(live > T, 1, state.overflow),
    )


apply_cells_batch_jit = jax.jit(apply_cells_batch, donate_argnums=0,
                                static_argnums=4)


def matrix_cells_digest(state: MatrixCellState) -> jax.Array:
    """Order-invariant digest of the live cell set for cross-replica checks
    (the race-detection analog, SURVEY.md §5.2)."""
    live = state.key != EMPTY_KEY
    mix = state.key * jnp.int32(1000003) + state.value * jnp.int32(8191) \
        + state.seq
    return jnp.sum(jnp.where(live, mix, 0)) + state.count


class TensorMatrixStore:
    """Host facade: one SharedMatrix document's cells resident on device.

    Interns (rowKey, colKey) identities and JSON values to int32 handles,
    packs sequenced set-cell records into (O,) batches, merges them in one
    jit'd call, and reads back cells. Row/col axis merges (the permutation
    trees) live in ``models.SharedMatrix``; this is the serving-side cell
    engine (BASELINE config #3).
    """

    def __init__(self, capacity: int, batch_size: int = 4096):
        self.capacity = capacity
        self.batch = batch_size
        self.state = MatrixCellState.create(capacity)
        self._cell_ids: Dict[Tuple, int] = {}
        self._interner = ValueInterner()
        self.fww = False

    def cell_id(self, row_key, col_key) -> int:
        k = (row_key, col_key)
        if k not in self._cell_ids:
            self._cell_ids[k] = len(self._cell_ids)
        return self._cell_ids[k]

    def value_handle(self, value) -> int:
        return self._interner.handle(value)

    def switch_set_cell_policy(self) -> None:
        """One-way LWW → FWW switch (reference ``switchSetCellPolicy``)."""
        self.fww = True

    def apply_batch(self, records) -> None:
        """records: iterable of (row_key, col_key, value, seq), seq ascending."""
        recs = [(self.cell_id(r, c), int(s), self.value_handle(v))
                for r, c, v, s in records]
        for i in range(0, len(recs), self.batch):
            chunk = recs[i:i + self.batch]
            pad = self.batch - len(chunk)
            key = np.fromiter((k for k, _, _ in chunk), np.int32,
                              len(chunk))
            seq = np.fromiter((s for _, s, _ in chunk), np.int32,
                              len(chunk))
            val = np.fromiter((v for _, _, v in chunk), np.int32,
                              len(chunk))
            if pad:
                key = np.concatenate([key, np.full(pad, EMPTY_KEY)])
                seq = np.concatenate([seq, np.zeros(pad, np.int32)])
                val = np.concatenate([val, np.zeros(pad, np.int32)])
            self.state = apply_cells_batch_jit(
                self.state, jnp.asarray(key), jnp.asarray(seq),
                jnp.asarray(val), self.fww)

    def read_cell(self, cell: Tuple):
        """One cell's value without the full-table readback: the table is
        key-sorted on device, so a searchsorted probe + two scalar reads
        replace the O(capacity) transfer ``read_cells`` pays."""
        cid = self._cell_ids.get(cell)
        if cid is None:
            return None
        idx = int(jnp.searchsorted(self.state.key, jnp.int32(cid)))
        if idx >= self.capacity or int(self.state.key[idx]) != cid:
            return None
        return self._interner.value(int(self.state.value[idx]))

    def read_cells(self) -> dict:
        """{(rowKey, colKey): value} for all live cells."""
        keys = np.asarray(self.state.key)
        vals = np.asarray(self.state.value)
        live = keys != EMPTY_KEY
        by_id = {int(k): int(v) for k, v in zip(keys[live], vals[live])}
        return {cell: self._interner.value(by_id[cid])
                for cell, cid in self._cell_ids.items() if cid in by_id}

    def overflowed(self) -> bool:
        return bool(np.asarray(self.state.overflow))

    # ----------------------------------------------------- snapshot / resume

    def snapshot(self) -> dict:
        return {
            "key": np.asarray(self.state.key).copy(),
            "seq": np.asarray(self.state.seq).copy(),
            "value": np.asarray(self.state.value).copy(),
            "count": int(np.asarray(self.state.count)),
            "overflow": int(np.asarray(self.state.overflow)),
            "batch": self.batch,
            "cell_ids": list(self._cell_ids.items()),
            "values": self._interner.export(),
            "fww": self.fww,
        }

    @classmethod
    def restore(cls, snap: dict) -> "TensorMatrixStore":
        store = cls.__new__(cls)
        store.capacity = snap["key"].shape[0]
        store.batch = snap["batch"]
        store.state = MatrixCellState(
            key=jnp.asarray(snap["key"]), seq=jnp.asarray(snap["seq"]),
            value=jnp.asarray(snap["value"]),
            count=jnp.asarray(snap["count"], jnp.int32),
            overflow=jnp.asarray(snap["overflow"], jnp.int32))
        store._cell_ids = {tuple_key(k): v for k, v in snap["cell_ids"]}
        store._interner = ValueInterner.restore(snap["values"])
        store.fww = snap["fww"]
        return store


def tuple_key(k):
    """Recursively re-tuple a cell identity (snapshot transports may have
    turned nested tuples into lists)."""
    return tuple(tuple_key(x) if isinstance(x, (list, tuple)) else x
                 for x in k)
