"""Batched SharedMatrix cell-merge kernel: sorted sparse cell table on device.

Reference counterpart: ``@fluidframework/matrix`` cell storage
(``SparseArray2D`` + LWW set-cell conflict policy, with the one-way
``switchSetCellPolicy`` flip to first-writer-wins) — SURVEY.md §2.4 (mount
empty). Row/col permutation merges stay on the host's MergeTree-backed axes
(``models.shared_matrix``); what reaches the device is the cell-write hot
path: a stream of (cellId, seq, value) records to merge LWW into the
persistent cell set — BASELINE config #3's 1k×1k concurrent-edit storm.

TPU-first design: scatter-by-cell (the "obvious" layout) measures ~160k
ops/s on this chip because XLA scatter serializes; a multi-operand bitonic
``lax.sort`` of >1M rows runs in ~28 ms. So the state is a **sorted sparse
table** of (cell key, seq, value) and a batch merge is:

    concat(table, batch) → sort by (key, seq) → mark per-key winner →
    demote losers to EMPTY_KEY → sort by key → truncate to capacity

Two sorts, zero gathers/scatters. Empty slots carry ``EMPTY_KEY`` so they
sort to the tail and truncation only ever drops empties (a sticky overflow
flag is set if a live entry would fall off — the host's cue to re-bucket,
same escape hatch as ``StringState``).

Cell identity: the host interns each resolved (rowKey, colKey) identity —
stable across concurrent row/col inserts because identities come from the
permutation trees, not positions — to a dense int32 cell id.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .schema import ValueInterner

EMPTY_KEY = np.int32(2**31 - 1)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MatrixCellState:
    """Device-resident sorted sparse cell table (capacity T rows)."""

    key: jax.Array      # (T,) int32 cell id, EMPTY_KEY in free slots
    seq: jax.Array      # (T,) int32 seq of the winning write
    value: jax.Array    # (T,) int32 payload handle
    count: jax.Array    # ()   int32 live entries
    overflow: jax.Array  # ()  int32 sticky overflow flag

    @staticmethod
    def create(capacity: int) -> "MatrixCellState":
        return MatrixCellState(
            key=jnp.full((capacity,), EMPTY_KEY, jnp.int32),
            seq=jnp.zeros((capacity,), jnp.int32),
            value=jnp.zeros((capacity,), jnp.int32),
            count=jnp.zeros((), jnp.int32),
            overflow=jnp.zeros((), jnp.int32),
        )


def apply_cells_batch(state: MatrixCellState, op_key, op_seq, op_value,
                      fww=False) -> MatrixCellState:
    """Merge a (O,) batch of sequenced set-cell ops into the cell table.

    op_key/op_seq/op_value: (O,) int32; NOOP pads carry EMPTY_KEY. ``fww``
    switches the conflict policy to first-writer-wins (earliest acked seq
    keeps the cell — the reference's ``switchSetCellPolicy``); existing
    table entries still count as earlier writers via their stored seq.
    """
    T = state.key.shape[0]
    keys = jnp.concatenate([state.key, op_key])
    seqs = jnp.concatenate([state.seq, op_seq])
    vals = jnp.concatenate([state.value, op_value])

    keys, seqs, vals = jax.lax.sort([keys, seqs, vals], num_keys=2,
                                    is_stable=False)
    nxt_same = jnp.concatenate(
        [keys[1:] == keys[:-1], jnp.zeros((1,), bool)])
    prv_same = jnp.concatenate(
        [jnp.zeros((1,), bool), keys[1:] == keys[:-1]])
    win = jnp.where(fww, ~prv_same, ~nxt_same) & (keys != EMPTY_KEY)

    keys = jnp.where(win, keys, EMPTY_KEY)
    keys, seqs, vals = jax.lax.sort([keys, seqs, vals], num_keys=1,
                                    is_stable=False)
    live = jnp.sum((keys != EMPTY_KEY).astype(jnp.int32))
    return MatrixCellState(
        key=keys[:T], seq=seqs[:T], value=vals[:T],
        count=jnp.minimum(live, T),
        overflow=jnp.where(live > T, 1, state.overflow),
    )


apply_cells_batch_jit = jax.jit(apply_cells_batch, donate_argnums=0,
                                static_argnums=4)


def apply_cells_prefix(state: MatrixCellState, op_key, op_seq, op_value,
                       L: int, fww=False) -> MatrixCellState:
    """Capacity-independent merge: live entries occupy a key-sorted
    prefix bounded by the interned-identity count (keys are dense
    interned ids), so only ``table[:L]`` participates — the host picks L
    as the next pow2 ≥ the identity count (jit retraces only on pow2
    growth); rows past L are EMPTY_KEY by the sorted invariant and pass
    through untouched.

    Unlike the full-table kernel this never re-sorts the table: the
    prefix is ALREADY key-sorted, so only the (O,) batch is sorted and
    the two streams meet in a rank merge — merge positions come from two
    ``searchsorted`` passes and every data movement is a gather (XLA
    scatters and wide multi-operand sorts are the slow primitives on
    both CPU and TPU backends; see the module docstring). Equal keys
    tie-break table-before-batch, which is exact: sequenced batch seqs
    are strictly newer than any stored seq."""
    T = state.key.shape[0]
    tk, ts, tv = state.key[:L], state.seq[:L], state.value[:L]
    ok, osq, ov = jax.lax.sort([op_key, op_seq, op_value], num_keys=2,
                               is_stable=False)
    O = ok.shape[0]
    N = L + O
    # merged position of batch element j: j + (# table keys ≤ its key)
    pos_b = jnp.arange(O, dtype=jnp.int32) + jnp.searchsorted(
        tk, ok, side="right").astype(jnp.int32)
    # invert the merge by counting: at merged position p there are
    # b_cnt batch elements in [0, p]; p is a batch slot iff pos_b
    # lands on it, else it takes table element p - b_cnt
    p_arr = jnp.arange(N, dtype=jnp.int32)
    b_cnt = jnp.searchsorted(pos_b, p_arr, side="right").astype(jnp.int32)
    jb = jnp.maximum(b_cnt - 1, 0)
    is_b = (b_cnt > 0) & (pos_b[jb] == p_arr)
    ja = jnp.minimum(p_arr - b_cnt, L - 1)
    mk = jnp.where(is_b, ok[jb], tk[ja])
    ms = jnp.where(is_b, osq[jb], ts[ja])
    mv = jnp.where(is_b, ov[jb], tv[ja])
    nxt_same = jnp.concatenate(
        [mk[1:] == mk[:-1], jnp.zeros((1,), bool)])
    prv_same = jnp.concatenate(
        [jnp.zeros((1,), bool), mk[1:] == mk[:-1]])
    win = jnp.where(fww, ~prv_same, ~nxt_same) & (mk != EMPTY_KEY)
    # winner compaction, also by gather: output slot q holds the q-th
    # winner — the first merged position with cumulative win count q+1
    c = jnp.cumsum(win.astype(jnp.int32))
    live = c[-1]
    wq = jnp.searchsorted(
        c, jnp.arange(1, L + 1, dtype=jnp.int32), side="left")
    wq = jnp.minimum(wq, N - 1)
    keep = jnp.arange(L, dtype=jnp.int32) < live
    return MatrixCellState(
        key=jnp.concatenate(
            [jnp.where(keep, mk[wq], EMPTY_KEY), state.key[L:]]),
        seq=jnp.concatenate(
            [jnp.where(keep, ms[wq], 0), state.seq[L:]]),
        value=jnp.concatenate(
            [jnp.where(keep, mv[wq], 0), state.value[L:]]),
        count=jnp.minimum(live, T),
        overflow=jnp.where(live > L, 1, state.overflow),
    )


apply_cells_prefix_jit = jax.jit(apply_cells_prefix, donate_argnums=0,
                                 static_argnums=(4, 5))


def matrix_cells_digest(state: MatrixCellState) -> jax.Array:
    """Order-invariant digest of the live cell set for cross-replica checks
    (the race-detection analog, SURVEY.md §5.2)."""
    live = state.key != EMPTY_KEY
    mix = state.key * jnp.int32(1000003) + state.value * jnp.int32(8191) \
        + state.seq
    return jnp.sum(jnp.where(live, mix, 0)) + state.count


def _intern_values_column(interner: ValueInterner, values) -> np.ndarray:
    """Value handles for a whole cell column. Homogeneous-int columns (the
    volume case) intern one handle per UNIQUE value and gather; the type
    probe is exact (``bool`` is excluded — ``True`` and ``1`` canonicalize
    to different JSON) so the general path keeps full fidelity."""
    if set(map(type, values)) == {int}:
        u, inv = np.unique(np.asarray(values, np.int64),
                           return_inverse=True)
        return np.asarray(interner.bulk_ints(u.tolist()), np.int32)[inv]
    return np.asarray(interner.bulk(values), np.int32)


class TensorMatrixStore:
    """Host facade: one SharedMatrix document's cells resident on device.

    Interns (rowKey, colKey) identities and JSON values to int32 handles,
    packs sequenced set-cell records into (O,) batches, merges them in one
    jit'd call, and reads back cells. Row/col axis merges (the permutation
    trees) live in ``models.SharedMatrix``; this is the serving-side cell
    engine (BASELINE config #3).
    """

    def __init__(self, capacity: int, batch_size: int = 4096):
        self.capacity = capacity
        self.batch = batch_size
        self.state = MatrixCellState.create(capacity)
        self._cell_ids: Dict[Tuple, int] = {}
        self._interner = ValueInterner()
        self.fww = False

    def cell_id(self, row_key, col_key) -> int:
        k = (row_key, col_key)
        if k not in self._cell_ids:
            self._cell_ids[k] = len(self._cell_ids)
        return self._cell_ids[k]

    def capacity_stats(self) -> dict:
        """Capacity-plane report fragment (ISSUE 19)."""
        from ..utils import capacity as _cap
        host = _cap.dict_nbytes(len(self._cell_ids),
                                _cap.INT_DICT_ENTRY_BYTES + 56)
        host += _cap.interner_nbytes(len(self._interner),
                                     80 * len(self._interner))
        return {"host": {"interner": int(host)},
                "device": {"state": _cap.device_nbytes(self.state)}}

    def value_handle(self, value) -> int:
        return self._interner.handle(value)

    def conservative_room(self, extra: int) -> bool:
        """Can ``extra`` more distinct identities still fit the table?"""
        return len(self._cell_ids) + extra < self.capacity

    def switch_set_cell_policy(self) -> None:
        """One-way LWW → FWW switch (reference ``switchSetCellPolicy``)."""
        self.fww = True

    def _merge_chunk(self, key, seq, val) -> None:
        """One padded-chunk merge dispatch, prefix-sized when the table
        is mostly free: live ≤ interned identities, so a pow2 prefix
        bound keeps the sort cost proportional to the LIVE table."""
        L = 8
        need = min(len(self._cell_ids) + 1, self.capacity)
        while L < need:
            L *= 2
        if L >= self.capacity:
            self.state = apply_cells_batch_jit(
                self.state, jnp.asarray(key), jnp.asarray(seq),
                jnp.asarray(val), self.fww)
        else:
            self.state = apply_cells_prefix_jit(
                self.state, jnp.asarray(key), jnp.asarray(seq),
                jnp.asarray(val), L, self.fww)

    def apply_batch(self, records) -> None:
        """records: iterable of (row_key, col_key, value, seq), seq ascending."""
        recs = [(self.cell_id(r, c), int(s), self.value_handle(v))
                for r, c, v, s in records]
        for i in range(0, len(recs), self.batch):
            chunk = recs[i:i + self.batch]
            pad = self.batch - len(chunk)
            key = np.fromiter((k for k, _, _ in chunk), np.int32,
                              len(chunk))
            seq = np.fromiter((s for _, s, _ in chunk), np.int32,
                              len(chunk))
            val = np.fromiter((v for _, _, v in chunk), np.int32,
                              len(chunk))
            if pad:
                key = np.concatenate([key, np.full(pad, EMPTY_KEY)])
                seq = np.concatenate([seq, np.zeros(pad, np.int32)])
                val = np.concatenate([val, np.zeros(pad, np.int32)])
            self._merge_chunk(key, seq, val)

    def apply_batch_columnar(self, row_keys, col_keys, values,
                             seqs) -> None:
        """Columnar twin of ``apply_batch``: prebuilt key-tuple columns +
        a value column + an int seq array. One tight bulk pass per intern
        table and array-sliced chunk packing — no per-record tuple churn
        or ``fromiter`` scans (the matrix serving hot path)."""
        n = len(row_keys)
        if not n:
            return
        ids = self._cell_ids
        get = ids.get
        key = np.empty(n, np.int32)
        i = 0
        for rk, ck in zip(row_keys, col_keys):
            k = (rk, ck)
            h = get(k)
            if h is None:
                h = len(ids)
                ids[k] = h
            key[i] = h
            i += 1
        val = _intern_values_column(self._interner, values)
        seqs = np.ascontiguousarray(seqs, np.int32)
        for i in range(0, n, self.batch):
            kc = key[i:i + self.batch]
            sc = seqs[i:i + self.batch]
            vc = val[i:i + self.batch]
            pad = self.batch - len(kc)
            if pad:
                kc = np.concatenate([kc, np.full(pad, EMPTY_KEY,
                                                 np.int32)])
                sc = np.concatenate([sc, np.zeros(pad, np.int32)])
                vc = np.concatenate([vc, np.zeros(pad, np.int32)])
            self._merge_chunk(kc, sc, vc)

    def read_cell(self, cell: Tuple):
        """One cell's value without the full-table readback: the table is
        key-sorted on device, so a searchsorted probe + two scalar reads
        replace the O(capacity) transfer ``read_cells`` pays."""
        cid = self._cell_ids.get(cell)
        if cid is None:
            return None
        idx = int(jnp.searchsorted(self.state.key, jnp.int32(cid)))
        if idx >= self.capacity or int(self.state.key[idx]) != cid:
            return None
        return self._interner.value(int(self.state.value[idx]))

    def read_cells(self) -> dict:
        """{(rowKey, colKey): value} for all live cells."""
        keys = np.asarray(self.state.key)
        vals = np.asarray(self.state.value)
        live = keys != EMPTY_KEY
        by_id = {int(k): int(v) for k, v in zip(keys[live], vals[live])}
        return {cell: self._interner.value(by_id[cid])
                for cell, cid in self._cell_ids.items() if cid in by_id}

    def overflowed(self) -> bool:
        return bool(np.asarray(self.state.overflow))

    # ----------------------------------------------------- snapshot / resume

    def snapshot(self) -> dict:
        return {
            "key": np.asarray(self.state.key).copy(),
            "seq": np.asarray(self.state.seq).copy(),
            "value": np.asarray(self.state.value).copy(),
            "count": int(np.asarray(self.state.count)),
            "overflow": int(np.asarray(self.state.overflow)),
            "batch": self.batch,
            "cell_ids": list(self._cell_ids.items()),
            "values": self._interner.export(),
            "fww": self.fww,
        }

    def table_bases(self) -> dict:
        """Append-only table lengths (incremental-summary baselines)."""
        return {"cell_ids": len(self._cell_ids),
                "values": len(self._interner)}

    def snapshot_delta(self, bases: dict) -> dict:
        """Incremental snapshot: the live-trimmed cell planes (the table
        is key-sorted and globally re-sorted every merge, so cell deltas
        are whole-pool — bounded by LIVE CELLS, not history) plus the
        append-only identity/value table deltas since ``bases``."""
        import itertools
        n = max(int(np.asarray(self.state.count)), 0)
        return {
            "key": np.asarray(self.state.key)[:n].copy(),
            "seq": np.asarray(self.state.seq)[:n].copy(),
            "value": np.asarray(self.state.value)[:n].copy(),
            "count": n,
            "overflow": int(np.asarray(self.state.overflow)),
            "fww": self.fww,
            "cell_ids_delta": list(itertools.islice(
                self._cell_ids.items(), bases["cell_ids"], None)),
            "values_delta": self._interner.export_from(bases["values"]),
        }

    def apply_delta(self, delta: dict) -> None:
        """Fold one ``snapshot_delta`` into this (restored-base) store:
        replace the cell planes, extend the append-only tables."""
        n = delta["count"]
        key = np.full((self.capacity,), EMPTY_KEY, np.int32)
        seq = np.zeros((self.capacity,), np.int32)
        val = np.zeros((self.capacity,), np.int32)
        key[:n] = delta["key"]
        seq[:n] = delta["seq"]
        val[:n] = delta["value"]
        self.state = MatrixCellState(
            key=jnp.asarray(key), seq=jnp.asarray(seq),
            value=jnp.asarray(val),
            count=jnp.asarray(n, jnp.int32),
            overflow=jnp.asarray(delta["overflow"], jnp.int32))
        for k, v in delta["cell_ids_delta"]:
            self._cell_ids[tuple_key(k)] = v
        self._interner.extend_from(delta["values_delta"])
        self.fww = delta["fww"]

    @classmethod
    def restore(cls, snap: dict) -> "TensorMatrixStore":
        store = cls.__new__(cls)
        store.capacity = snap["key"].shape[0]
        store.batch = snap["batch"]
        store.state = MatrixCellState(
            key=jnp.asarray(snap["key"]), seq=jnp.asarray(snap["seq"]),
            value=jnp.asarray(snap["value"]),
            count=jnp.asarray(snap["count"], jnp.int32),
            overflow=jnp.asarray(snap["overflow"], jnp.int32))
        store._cell_ids = {tuple_key(k): v for k, v in snap["cell_ids"]}
        store._interner = ValueInterner.restore(snap["values"])
        store.fww = snap["fww"]
        return store


def tuple_key(k):
    """Recursively re-tuple a cell identity (snapshot transports may have
    turned nested tuples into lists)."""
    return tuple(tuple_key(x) if isinstance(x, (list, tuple)) else x
                 for x in k)


class ShardedMatrixStore:
    """Doc-sharded cell pools (mesh mode): shard ``s`` owns the cells of
    doc rows ``[s·D/S, (s+1)·D/S)``. Cells are doc-scoped — the doc row
    is the first component of every cell identity ``((row, rowKey),
    colKey)`` — so routing by owning doc keeps the sort-merge entirely
    shard-local: the sharded apply is a collective-free shard_map of the
    same ``apply_cells_batch`` (SURVEY.md §2.14 doc-DP for the matrix
    cell volume). Same host API as ``TensorMatrixStore``."""

    def __init__(self, capacity: int, mesh, n_docs: int,
                 batch_size: int = 4096):
        s = mesh.devices.size
        if capacity % s:
            raise ValueError(f"cell capacity {capacity} not divisible by "
                             f"mesh size {s}")
        if n_docs % s:
            raise ValueError(f"n_docs {n_docs} not divisible by mesh "
                             f"size {s}")
        self.capacity = capacity          # total across shards
        self.shard_capacity = capacity // s
        self.n_shards = s
        self.n_docs = n_docs
        self.mesh = mesh
        self.batch = batch_size
        self.state = MatrixCellState(
            key=jnp.full((s, self.shard_capacity), EMPTY_KEY, jnp.int32),
            seq=jnp.zeros((s, self.shard_capacity), jnp.int32),
            value=jnp.zeros((s, self.shard_capacity), jnp.int32),
            count=jnp.zeros((s,), jnp.int32),
            overflow=jnp.zeros((s,), jnp.int32))
        self._place()
        self._cell_ids: Dict[Tuple, int] = {}
        self._shard_counts = [0] * s     # interned identities per shard
        self._interner = ValueInterner()
        self.fww = False

    def _place(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel.mesh import DOC_AXIS
        row = NamedSharding(self.mesh, P(DOC_AXIS, None))
        one = NamedSharding(self.mesh, P(DOC_AXIS))
        self.state = MatrixCellState(
            key=jax.device_put(self.state.key, row),
            seq=jax.device_put(self.state.seq, row),
            value=jax.device_put(self.state.value, row),
            count=jax.device_put(self.state.count, one),
            overflow=jax.device_put(self.state.overflow, one))

    def shard_of_row(self, row: int) -> int:
        return row * self.n_shards // self.n_docs

    def cell_id(self, row_key, col_key) -> int:
        k = (row_key, col_key)
        if k not in self._cell_ids:
            self._cell_ids[k] = len(self._cell_ids)
            self._shard_counts[self.shard_of_row(row_key[0])] += 1
        return self._cell_ids[k]

    def value_handle(self, value) -> int:
        return self._interner.handle(value)

    def capacity_stats(self) -> dict:
        """Capacity-plane report fragment (ISSUE 19)."""
        from ..utils import capacity as _cap
        host = _cap.dict_nbytes(len(self._cell_ids),
                                _cap.INT_DICT_ENTRY_BYTES + 56)
        host += _cap.interner_nbytes(len(self._interner),
                                     80 * len(self._interner))
        return {"host": {"interner": int(host)},
                "device": {"state": _cap.device_nbytes(self.state)}}

    def conservative_room(self, extra: int) -> bool:
        """Worst case: every pending cell mints on the fullest shard."""
        return max(self._shard_counts) + extra < self.shard_capacity

    def switch_set_cell_policy(self) -> None:
        self.fww = True

    def apply_batch(self, records) -> None:
        """records: iterable of (row_key, col_key, value, seq), seq
        ascending; row_key = (doc_row, resolved key) — the doc row routes
        the write to its owning shard's pool."""
        per_shard: List[list] = [[] for _ in range(self.n_shards)]
        for r, c, v, q in records:
            per_shard[self.shard_of_row(r[0])].append(
                (self.cell_id(r, c), int(q), self.value_handle(v)))
        widest = max((len(p) for p in per_shard), default=0)
        if not widest:
            return
        for base in range(0, widest, self.batch):
            o = min(self.batch, widest - base)
            o2 = 8
            while o2 < o:
                o2 *= 2
            key = np.full((self.n_shards, o2), EMPTY_KEY, np.int32)
            seq = np.zeros((self.n_shards, o2), np.int32)
            val = np.zeros((self.n_shards, o2), np.int32)
            for s, recs in enumerate(per_shard):
                chunk = recs[base:base + self.batch]
                if not chunk:
                    continue
                arr = np.array(chunk, np.int32)
                key[s, :len(chunk)] = arr[:, 0]
                seq[s, :len(chunk)] = arr[:, 1]
                val[s, :len(chunk)] = arr[:, 2]
            from ..parallel.sharded import sharded_cells_apply
            self.state = sharded_cells_apply(self.mesh, self.fww)(
                self.state, jnp.asarray(key), jnp.asarray(seq),
                jnp.asarray(val))

    def apply_batch_columnar(self, row_keys, col_keys, values,
                             seqs) -> None:
        """Columnar twin of ``apply_batch`` with the same doc-row shard
        routing (``row_key[0]``); stable per-shard partition keeps each
        shard's stream seq-ascending."""
        n = len(row_keys)
        if not n:
            return
        ids = self._cell_ids
        get = ids.get
        counts = self._shard_counts
        ns, nd = self.n_shards, self.n_docs
        key = np.empty(n, np.int32)
        shard = np.empty(n, np.int32)
        i = 0
        for rk, ck in zip(row_keys, col_keys):
            k = (rk, ck)
            s = rk[0] * ns // nd
            h = get(k)
            if h is None:
                h = len(ids)
                ids[k] = h
                counts[s] += 1
            key[i] = h
            shard[i] = s
            i += 1
        val = _intern_values_column(self._interner, values)
        seqs = np.ascontiguousarray(seqs, np.int32)
        order = np.argsort(shard, kind="stable")
        bounds = np.searchsorted(shard[order], np.arange(ns + 1))
        widest = int(np.diff(bounds).max())
        from ..parallel.sharded import sharded_cells_apply
        for base in range(0, widest, self.batch):
            o = min(self.batch, widest - base)
            o2 = 8
            while o2 < o:
                o2 *= 2
            keyp = np.full((ns, o2), EMPTY_KEY, np.int32)
            seqp = np.zeros((ns, o2), np.int32)
            valp = np.zeros((ns, o2), np.int32)
            for s in range(ns):
                idx = order[bounds[s]:bounds[s + 1]][
                    base:base + self.batch]
                if not len(idx):
                    continue
                keyp[s, :len(idx)] = key[idx]
                seqp[s, :len(idx)] = seqs[idx]
                valp[s, :len(idx)] = val[idx]
            self.state = sharded_cells_apply(self.mesh, self.fww)(
                self.state, jnp.asarray(keyp), jnp.asarray(seqp),
                jnp.asarray(valp))

    def read_cell(self, cell: Tuple):
        cid = self._cell_ids.get(cell)
        if cid is None:
            return None
        s = self.shard_of_row(cell[0][0])
        keys = self.state.key[s]
        idx = int(jnp.searchsorted(keys, jnp.int32(cid)))
        if idx >= self.shard_capacity or int(keys[idx]) != cid:
            return None
        return self._interner.value(int(self.state.value[s, idx]))

    def read_cells(self) -> dict:
        keys = np.asarray(self.state.key).reshape(-1)
        vals = np.asarray(self.state.value).reshape(-1)
        live = keys != EMPTY_KEY
        by_id = {int(k): int(v) for k, v in zip(keys[live], vals[live])}
        return {cell: self._interner.value(by_id[cid])
                for cell, cid in self._cell_ids.items() if cid in by_id}

    def overflowed(self) -> bool:
        return bool(np.asarray(self.state.overflow).any())

    # ----------------------------------------------------- snapshot / resume

    def snapshot(self) -> dict:
        return {
            "key": np.asarray(self.state.key).copy(),
            "seq": np.asarray(self.state.seq).copy(),
            "value": np.asarray(self.state.value).copy(),
            "count": np.asarray(self.state.count).copy(),
            "overflow": np.asarray(self.state.overflow).copy(),
            "batch": self.batch,
            "cell_ids": list(self._cell_ids.items()),
            "values": self._interner.export(),
            "fww": self.fww,
            "sharded_docs": self.n_docs,
        }

    @classmethod
    def restore(cls, snap: dict, mesh) -> "ShardedMatrixStore":
        s, t_s = snap["key"].shape
        store = cls(s * t_s, mesh, snap["sharded_docs"],
                    batch_size=snap["batch"])
        store.state = MatrixCellState(
            key=jnp.asarray(snap["key"]), seq=jnp.asarray(snap["seq"]),
            value=jnp.asarray(snap["value"]),
            count=jnp.asarray(snap["count"], jnp.int32),
            overflow=jnp.asarray(snap["overflow"], jnp.int32))
        store._place()
        for k, v in snap["cell_ids"]:
            ck = tuple_key(k)
            store._cell_ids[ck] = v
            store._shard_counts[store.shard_of_row(ck[0][0])] += 1
        store._interner = ValueInterner.restore(snap["values"])
        store.fww = snap["fww"]
        return store

    def table_bases(self) -> dict:
        return {"cell_ids": len(self._cell_ids),
                "values": len(self._interner)}

    def snapshot_delta(self, bases: dict) -> dict:
        """Per-shard live-trimmed planes + append-only table deltas (same
        contract as TensorMatrixStore.snapshot_delta)."""
        import itertools
        counts = np.asarray(self.state.count)
        w = max(int(counts.max()), 1)
        return {
            "key": np.asarray(self.state.key)[:, :w].copy(),
            "seq": np.asarray(self.state.seq)[:, :w].copy(),
            "value": np.asarray(self.state.value)[:, :w].copy(),
            "count": counts.copy(),
            "overflow": np.asarray(self.state.overflow).copy(),
            "fww": self.fww,
            "cell_ids_delta": list(itertools.islice(
                self._cell_ids.items(), bases["cell_ids"], None)),
            "values_delta": self._interner.export_from(bases["values"]),
        }

    def apply_delta(self, delta: dict) -> None:
        w = delta["key"].shape[1]
        key = np.full((self.n_shards, self.shard_capacity), EMPTY_KEY,
                      np.int32)
        seq = np.zeros((self.n_shards, self.shard_capacity), np.int32)
        val = np.zeros((self.n_shards, self.shard_capacity), np.int32)
        key[:, :w] = delta["key"]
        seq[:, :w] = delta["seq"]
        val[:, :w] = delta["value"]
        self.state = MatrixCellState(
            key=jnp.asarray(key), seq=jnp.asarray(seq),
            value=jnp.asarray(val),
            count=jnp.asarray(np.asarray(delta["count"], np.int32)),
            overflow=jnp.asarray(np.asarray(delta["overflow"],
                                            np.int32)))
        self._place()
        for k, v in delta["cell_ids_delta"]:
            ck = tuple_key(k)
            if ck not in self._cell_ids:
                self._shard_counts[self.shard_of_row(ck[0][0])] += 1
            self._cell_ids[ck] = v
        self._interner.extend_from(delta["values_delta"])
        self.fww = delta["fww"]
