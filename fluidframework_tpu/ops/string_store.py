"""Host facade for the batched merge-tree kernel: many SharedString documents
resident on device.

This is the serving/replica-side merge engine of the north star (sequenced
ops only); interactive optimistic editing remains in ``models.SharedString``.
The store interns variable-length payloads (text runs, markers) into an int32
handle table — the device does ordering/position math, never string bytes
(SURVEY.md §7.2) — and maps client ids to per-doc indexes for the remover
bitmask.
"""

from __future__ import annotations

import dataclasses
import functools
import heapq
import json
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.constants import NOT_REMOVED
from ..utils.telemetry import REGISTRY
from .merge_tree_kernel import (
    MAX_CLIENTS, PROP_HANDLE_BITS, StringState, _PLANES, apply_string_batch,
    apply_string_batch_jit, compact_string_state_jit, string_state_digest,
)
from .pallas_string_kernel import apply_string_batch_pallas
from .schema import OpKind, ValueInterner

_TEXT = 0
_MARKER = 1

# ---------------------------------------------------------- dispatch metrics
# The merge-tree/Pallas kernels were a dark layer: dispatches and XLA
# (re)compiles were invisible outside per-store ad-hoc counters. Every
# device dispatch counts into the process registry; compile-cache
# accounting compares the summed jit-cache sizes of this module's entry
# points before/after — growth means the dispatch paid an XLA compile,
# no growth means it hit the compile cache.

_JIT_FN_NAMES = (
    "_write_rows_jit", "_gather_rows_jit", "_write_row_jit",
    "_visible_lengths_jit", "_gather_doc_jit", "_apply_pallas_jit",
    "_columnar_unpack_jit", "_columnar_merge_jit",
    "apply_string_batch_jit", "compact_string_state_jit",
)
_jit_cache_total = 0


def _note_dispatch(kind: str, dispatch_ms: Optional[float] = None) -> None:
    global _jit_cache_total
    REGISTRY.inc("device_dispatches")
    REGISTRY.inc(f"device_dispatches_{kind}")
    if dispatch_ms is not None:
        REGISTRY.observe("device_dispatch_ms", dispatch_ms)
    size = 0
    for name in _JIT_FN_NAMES:
        cache_size = getattr(globals().get(name), "_cache_size", None)
        if cache_size is None:
            return  # jax without per-function cache introspection
        try:
            size += cache_size()
        except Exception:
            return
    if size > _jit_cache_total:
        REGISTRY.inc("jax_compiles", size - _jit_cache_total)
    else:
        REGISTRY.inc("jax_compile_cache_hits")
    # track shrinkage too (jax.clear_caches in tests resets the baseline)
    _jit_cache_total = size


@functools.partial(jax.jit, donate_argnums=0)
def _write_rows_jit(state, rows, seq, client, removed_seq, removers, length,
                    handle_op, handle_off, prop_val, count, overflow):
    """Batched overwrite of a subset of doc rows (incremental-summary
    restore): one scatter per plane, one dispatch total."""
    return StringState(
        seq=state.seq.at[rows].set(seq),
        client=state.client.at[rows].set(client),
        removed_seq=state.removed_seq.at[rows].set(removed_seq),
        removers=state.removers.at[rows].set(removers),
        length=state.length.at[rows].set(length),
        handle_op=state.handle_op.at[rows].set(handle_op),
        handle_off=state.handle_off.at[rows].set(handle_off),
        prop_val=state.prop_val.at[rows].set(prop_val),
        count=state.count.at[rows].set(count),
        overflow=state.overflow.at[rows].set(overflow),
    )


@jax.jit
def _gather_rows_jit(state, rows):
    """(plane subsets for a row list) in ONE device→host round-trip —
    the incremental-summary gather (dirty rows only)."""
    return (state.seq[rows], state.client[rows], state.removed_seq[rows],
            state.removers[rows], state.length[rows],
            state.handle_op[rows], state.handle_off[rows],
            state.prop_val[rows], state.count[rows], state.overflow[rows])


@functools.partial(jax.jit, donate_argnums=0)
def _write_row_jit(state, row, seq, client, removed_seq, removers, length,
                   handle_op, handle_off, prop_val, count):
    """Overwrite one doc row's planes in a single dispatch (overflow
    recovery re-upload); clears the row's sticky overflow flag."""
    return StringState(
        seq=state.seq.at[row].set(seq),
        client=state.client.at[row].set(client),
        removed_seq=state.removed_seq.at[row].set(removed_seq),
        removers=state.removers.at[row].set(removers),
        length=state.length.at[row].set(length),
        handle_op=state.handle_op.at[row].set(handle_op),
        handle_off=state.handle_off.at[row].set(handle_off),
        prop_val=state.prop_val.at[row].set(prop_val),
        count=state.count.at[row].set(count),
        overflow=state.overflow.at[row].set(0),
    )


@jax.jit
def _visible_lengths_jit(state):
    """(D,) visible length per doc — bulk read primitive."""
    S = state.seq.shape[1]
    active = jnp.arange(S)[None, :] < state.count[:, None]
    live = active & (state.removed_seq == NOT_REMOVED)
    return jnp.sum(jnp.where(live, state.length, 0), axis=1)


@jax.jit
def _gather_doc_jit(state, doc):
    """(6, S) stack of one doc's read planes + its slot count (row 5),
    so a read costs ONE device→host transfer."""
    return jnp.stack([
        state.removed_seq[doc], state.handle_op[doc], state.handle_off[doc],
        state.length[doc], state.seq[doc],
        jnp.full((state.seq.shape[1],), state.count[doc]),
    ])

# Pallas doc-axis tiles, widest first (T=128 measures fastest on v5e; smaller
# tiles let stores whose doc count is not 128-divisible still take the fused
# path). int32 sublane width is 8 — narrower tiles cannot compile.
_PALLAS_TILES = (128, 64, 32, 16, 8)


def pallas_tile_for(n_docs: int, capacity: int) -> Optional[int]:
    """Widest VMEM tile serving this store shape, or None if the fused
    kernel cannot run it (doc count not tile-divisible, or slot capacity
    not lane-aligned)."""
    if capacity % 128 != 0:
        return None
    for t in _PALLAS_TILES:
        if n_docs % t == 0:
            return t
    return None


@functools.partial(jax.jit, donate_argnums=0,
                   static_argnames=("tile", "interpret", "with_props"))
def _apply_pallas_jit(state, kind, a0, a1, a2, seq, client, ref_seq,
                      tile, interpret, with_props=False):
    return apply_string_batch_pallas(state, kind, a0, a1, a2, seq, client,
                                     ref_seq, tile=tile, interpret=interpret,
                                     with_props=with_props)


@functools.partial(jax.jit,
                   static_argnames=("R", "O", "pos_wide", "ref_wide",
                                    "rich", "n_docs", "fuse_compact",
                                    "scatter_rows", "compact8", "tab_n"))
def _columnar_unpack_jit(buf, R, O, pos_wide, ref_wide, rich, n_docs,
                         fuse_compact, scatter_rows, compact8=False,
                         tab_n=0):
    """Device-side unpack of ONE byte-packed columnar batch. The host
    concatenates every op plane into a single uint8 buffer — kind u8,
    client-idx u8, a0/a1 (i16, or i32 when ``pos_wide``), ref (u16 LAG
    behind the op's own seq, or full i32 when ``ref_wide``), a2 (one
    broadcast i32 handle, or an (N,) i32 plane when ``rich``), the
    per-row seq bases, the row indices, and the fused min_seq — because
    over a tunnel-attached device EACH transfer pays the link round-trip
    and the wire bytes ARE the columnar path's bottleneck (measured: 7
    per-plane transfers cost ~5× the fused apply itself; one fused buffer
    at 8 B/op restores the kernel rate).

    seq = base + running count of non-NOOP slots (nacked ops were
    NOOP-masked host-side and consumed no sequence number); ref clamps to
    seq-1 (mirroring Deli).

    ``rich`` payload modes: 0 = broadcast (one i32 handle), 1 = a full
    (N,) i32 a2 plane, 2/3 = TABLE form — the wire carries a u8 (mode 2)
    or u16 (mode 3) table index per op plus two small i32 tables
    (``tab_n`` entries each, padded to a power of two): the a2 value
    (payload handle / packed property) and the insert length. The device
    gathers a2 and insert a1 from the tables, so rich batches cost ~1-2
    extra wire bytes per op instead of 4 and the host never materializes
    an (R, O) handle plane (the former rich-pack hot spot).

    This is deliberately its OWN jit (not fused into the merge program),
    and the buffer is INT32 WORDS unpacked by shift/mask — not u8 +
    bitcast: both the u8-bitcast form and fusing the unpack into the
    scan/compact body pathologically explode XLA's TPU compile time
    (seconds → many minutes at 10k-doc shapes, measured); this form
    compiles in seconds and the unpacked planes stay on device."""
    N = R * O

    def take_u8(off, n):
        w = -(-n // 4)
        words = jax.lax.slice_in_dim(buf, off, off + w, axis=0)
        v = jnp.stack([words & 0xFF, (words >> 8) & 0xFF,
                       (words >> 16) & 0xFF, (words >> 24) & 0xFF],
                      axis=1).reshape(4 * w)[:n]
        return v, off + w

    def take_u16(off, n):
        w = -(-n // 2)
        words = jax.lax.slice_in_dim(buf, off, off + w, axis=0)
        v = jnp.stack([words & 0xFFFF, (words >> 16) & 0xFFFF],
                      axis=1).reshape(2 * w)[:n]
        return v, off + w

    def take_i32(off, n):
        return jax.lax.slice_in_dim(buf, off, off + n, axis=0), off + n

    if compact8:
        # 5 B/op profile: [kind(2b)|cidx(6b)] u8, a0 u16, span-delta u8
        # (a1 = a0+delta for remove/annotate, payload length for insert),
        # lag u8. NOOP (=12) rides as code 3 in the 2-bit field.
        kc, off = take_u8(0, N)
        kind = kc & 0x3
        kind = jnp.where(kind == 3, int(OpKind.NOOP), kind)
        client = kc >> 2
        a0, off = take_u16(off, N)
        delta, off = take_u8(off, N)
        a1 = jnp.where(kind == int(OpKind.STR_INSERT), delta, a0 + delta)
        ref, off = take_u8(off, N)
    else:
        take_pos = take_i32 if pos_wide else take_u16
        kind, off = take_u8(0, N)
        client, off = take_u8(off, N)
        a0, off = take_pos(off, N)
        a1, off = take_pos(off, N)
        ref, off = (take_i32 if ref_wide else take_u16)(off, N)
    lenv = None
    if rich in (2, 3):
        ti, off = (take_u8 if rich == 2 else take_u16)(off, N)
        a2tab, off = take_i32(off, tab_n)
        lentab, off = take_i32(off, tab_n)
        ti = ti.reshape(R, O)
        a2 = a2tab[ti]
        lenv = lentab[ti]
    else:
        a2, off = take_i32(off, N if rich else 1)
    base, off = take_i32(off, R)
    rows, off = take_i32(off, R)
    min_seq, off = take_i32(off, n_docs if fuse_compact else 1)

    kind = kind.reshape(R, O)
    valid = kind != int(OpKind.NOOP)
    seq = base[:, None] + jnp.cumsum(valid.astype(jnp.int32), axis=1)
    a0 = a0.reshape(R, O)
    a1 = a1.reshape(R, O)
    client = client.reshape(R, O)
    if lenv is not None:  # table form: insert a1 = payload length
        a1 = jnp.where(kind == int(OpKind.STR_INSERT), lenv, a1)
    if ref_wide and not compact8:
        ref = jnp.minimum(ref.reshape(R, O), seq - 1)
    else:  # lag encoding: ref = seq - lag, lag >= 1 (the Deli clamp)
        ref = seq - jnp.maximum(ref.reshape(R, O), 1)
    if rich == 1:
        a2 = a2.reshape(R, O)
    elif not rich:
        a2 = jnp.broadcast_to(a2, (R, O))
    a2 = jnp.where((kind == int(OpKind.STR_INSERT))
                   | (kind == int(OpKind.STR_ANNOTATE)), a2, 0)
    planes = (kind, a0, a1, a2, seq, client, ref)
    if scatter_rows:
        def full(p, fill):
            return jnp.full((n_docs, O), fill, jnp.int32).at[rows].set(p)

        planes = (full(planes[0], int(OpKind.NOOP)),) + \
            tuple(full(p, 0) for p in planes[1:])
    return planes, min_seq


@functools.partial(jax.jit, donate_argnums=0,
                   static_argnames=("use_pallas", "tile", "interpret",
                                    "with_props", "fuse_compact"))
def _columnar_merge_jit(state, planes, min_seq, use_pallas, tile,
                        interpret, with_props, fuse_compact):
    """The merge half of the columnar apply (device-resident planes from
    ``_columnar_unpack_jit``): fused Pallas apply+zamboni when eligible,
    else the XLA scan (+ fused compact)."""
    if use_pallas:
        # fused apply+zamboni: ONE dispatch, planes stay in VMEM (the r1
        # headline configuration, now the product path)
        return apply_string_batch_pallas(
            state, *planes, tile=tile, interpret=interpret,
            min_seq=min_seq if fuse_compact else None,
            with_props=with_props)
    out = apply_string_batch(state, *planes, with_props=with_props)
    if fuse_compact:
        from .merge_tree_kernel import compact_string_state
        out = compact_string_state(out, min_seq, with_props)
    return out


class PrepackedPlanes:
    """The seq-independent half of a columnar apply's host pack: payload/
    props tables interned, wire form chosen, insert lengths resolved —
    everything ``apply_planes`` needs that does NOT depend on sequencing
    results. Produced by ``TensorStringStore.prepack_planes`` (the
    pipelined-ingest pack worker runs it concurrent with the previous
    wave's dispatch) and consumed exactly once, in submission order —
    payload-handle allocation happens at prepack time, so waves must be
    prepacked and applied FIFO or handle numbering diverges from a
    serial execution."""

    __slots__ = ("rich", "rich_mode", "a2_np", "tab_a2", "tab_len",
                 "tab_n", "tidx_eff", "a1", "prep_ms", "pooled")

    def __init__(self):
        self.rich = False
        self.rich_mode = 0
        self.a2_np = None
        self.tab_a2 = None
        self.tab_len = None
        self.tab_n = 0
        self.tidx_eff = None
        self.a1 = None
        self.prep_ms = 0.0
        self.pooled = False


class StringOpInterner:
    """Shared host-side message→op-record translation for the flat and
    mega-doc stores: payload/client/property interning and the
    insert-with-props → insert + same-seq annotate expansion. One
    implementation so the two serving facades cannot drift apart."""

    # every per-slot plane of StringState, derived so a future plane cannot
    # be silently dropped from either store's snapshots
    SNAP_PLANES = tuple(
        f.name for f in dataclasses.fields(StringState)
        if f.name not in ("count", "overflow"))

    def _init_interner(self, n_docs: int, n_props: int) -> None:
        self._payloads: List[Tuple[int, str]] = [(_TEXT, "")]  # handle 0
        # capacity plane (ISSUE 19): payload text chars, maintained O(1)
        # at every growth point so a census never walks the table
        self._payload_chars = 0
        self._client_idx: List[Dict[int, int]] = [dict()
                                                  for _ in range(n_docs)]
        # annotate: property KEYS intern to plane indexes (store-wide),
        # VALUES intern to handles; handle 0 = key unset (None deletes)
        self._prop_planes: Dict[str, int] = {}
        self._prop_values = ValueInterner()
        self._has_props = False
        self.n_props = n_props
        # one interner pass per UNIQUE (key, value): columnar annotate
        # tables re-pack the same few props every batch; the packed plane
        # <<20 | handle word is cached for hashable values (sound: planes
        # and value handles minted on the apply path are never released)
        self._props_pack_cache: Dict[tuple, int] = {}
        # (rows, client-column, lut) of the last single-writer columnar
        # batch: steady serving re-interns the same (row, client) pairs
        # every batch — a 40 KB memcmp replaces R dict hits
        self._cidx_cache: Optional[tuple] = None
        # pow2 payload-table buffer pool, keyed by tab_n: steady rich
        # serving re-packs same-capacity tables every wave; reusing the
        # buffers (zero only the stale tail) drops an alloc+full-zero per
        # wave. list ops are GIL-atomic, so a pipelined pack worker can
        # pop while the dispatch stage returns (see _tab_buffers).
        self._tab_pool: Dict[int, list] = {}

    def _client(self, doc: int, client_id: int) -> int:
        m = self._client_idx[doc]
        if client_id not in m:
            if len(m) >= MAX_CLIENTS:
                raise KeyError(f"doc {doc}: client capacity {MAX_CLIENTS}")
            m[client_id] = len(m)
        return m[client_id]

    def _payload(self, kind: int, text: str) -> int:
        self._payloads.append((kind, text))
        self._payload_chars += len(text)
        return len(self._payloads) - 1

    def _prop_plane(self, key: str) -> int:
        if key not in self._prop_planes:
            if len(self._prop_planes) >= self.n_props:
                raise KeyError(
                    f"property key capacity {self.n_props} exhausted "
                    f"(recreate the store with a larger n_props)")
            self._prop_planes[key] = len(self._prop_planes)
        return self._prop_planes[key]

    def _prop_handle(self, value) -> int:
        if value is None:
            return 0
        h = self._prop_values.handle(value)
        if h >= (1 << PROP_HANDLE_BITS):
            raise OverflowError("property value table exceeded 2^20 entries")
        return h

    def remap_payload_handles(self, src: "StringOpInterner",
                              handles: np.ndarray) -> np.ndarray:
        """Re-intern ``src``'s payloads referenced by ``handles`` into THIS
        store's table; returns the remapped handle array (dedup per distinct
        source handle). Used by the overflow-recovery re-upload."""
        hmap: Dict[int, int] = {}
        out = np.empty_like(handles)
        for i, h in enumerate(handles):
            h = int(h)
            if h not in hmap:
                kind, text = src._payloads[h]
                hmap[h] = self._payload(kind, text)
            out[i] = hmap[h]
        return out

    def remap_props(self, src: "StringOpInterner", tprop: np.ndarray,
                    out: np.ndarray) -> None:
        """Remap ``src``'s (n, K_src) per-slot property-value handles into
        ``out`` (n+, K_self) under THIS store's key planes and value table
        (overflow-recovery re-upload)."""
        n = tprop.shape[0]
        for key, tplane in src._prop_planes.items():
            mplane = self._prop_plane(key)
            col = tprop[:, tplane]
            vmap = {int(h): (0 if h == 0 else self._prop_values.handle(
                src._prop_values.value(int(h))))
                    for h in np.unique(col)}
            out[:n, mplane] = [vmap[int(h)] for h in col]

    def reserve_props(self, props: dict) -> list:
        """Admission-time reservation of the interner capacity ``props``
        will need at flush (serving engines call this BEFORE the op is
        sequenced/logged): mints planes for every new key now — atomically,
        nothing is minted if any key cannot fit — and checks value-table
        headroom without interning (conservative: values may dedupe at
        flush). Returns a token; pass it to ``release_props`` if the op is
        subsequently nacked, else the mint would leak the tiny plane table.
        Raises KeyError when capacity is exhausted."""
        new_keys = [k for k in props if k not in self._prop_planes]
        if len(self._prop_planes) + len(new_keys) > self.n_props:
            raise KeyError(
                f"property key capacity {self.n_props} exhausted")
        n_vals = sum(1 for v in props.values() if v is not None)
        if len(self._prop_values) + n_vals > (1 << PROP_HANDLE_BITS):
            raise KeyError("property value table exhausted")
        for k in new_keys:
            self._prop_plane(k)
        return new_keys

    def reserve_prop_tables(self, keys, values) -> None:
        """Columnar-ingest admission: reserve planes for every key in
        ``keys`` (atomic, as ``reserve_props``) and check value-table
        headroom for the DISTINCT uninterned values in ``values`` — the
        whole batch is admitted or none of it, before sequencing."""
        new_keys = [k for k in keys if k not in self._prop_planes]
        if len(self._prop_planes) + len(new_keys) > self.n_props:
            raise KeyError(
                f"property key capacity {self.n_props} exhausted")
        uniq = {json.dumps(v, sort_keys=True) for v in values
                if v is not None}
        uniq -= set(self._prop_values._ids)
        if len(self._prop_values) + len(uniq) > (1 << PROP_HANDLE_BITS):
            raise KeyError("property value table exhausted")
        for k in new_keys:
            self._prop_plane(k)

    def release_props(self, minted: list) -> None:
        """Undo ``reserve_props`` after a post-admission nack. Sound only
        within the submit's own synchronous window (no interleaved mint):
        planes are popped in reverse mint order, so indexes stay dense."""
        for k in reversed(minted):
            idx = self._prop_planes.pop(k)
            assert idx == len(self._prop_planes), "interleaved mint"

    def _annotate_rec(self, key, value, start, end, seq, cl, ref_seq):
        self._has_props = True
        packed = (self._prop_plane(key) << PROP_HANDLE_BITS) | \
            self._prop_handle(value)
        return (int(OpKind.STR_ANNOTATE), start, end, packed, seq, cl,
                ref_seq)

    def _records_for(self, doc: int, msg) -> list:
        """Device op records (7-tuples) for one sequenced message."""
        op = msg.contents
        cl = self._client(doc, msg.client_id)
        if op["mt"] == "insert":
            if op["kind"] == 1:  # marker
                handle = self._payload(_MARKER, "")
                length = 1
            else:
                if not op["text"]:
                    return []  # empty insert: no segment anywhere
                handle = self._payload(_TEXT, op["text"])
                length = len(op["text"])
            recs = [(int(OpKind.STR_INSERT), op["pos"], length, handle,
                     msg.seq, cl, msg.ref_seq)]
            # insert-with-props = insert + same-seq annotate of the new
            # segment: in the op's own perspective the inserted run occupies
            # exactly [pos, pos+len) and nothing else visible moved, so the
            # annotate targets only it
            for key in sorted(op.get("props") or {}):
                recs.append(self._annotate_rec(
                    key, op["props"][key], op["pos"], op["pos"] + length,
                    msg.seq, cl, msg.ref_seq))
            return recs
        if op["mt"] == "remove":
            return [(int(OpKind.STR_REMOVE), op["start"], op["end"], 0,
                     msg.seq, cl, msg.ref_seq)]
        if op["mt"] == "annotate":
            # one device record per property key (the kernel's per-key LWW
            # planes); all records share the message's seq
            return [self._annotate_rec(key, op["props"][key], op["start"],
                                       op["end"], msg.seq, cl, msg.ref_seq)
                    for key in sorted(op["props"])]
        raise ValueError(f"unknown op {op['mt']!r}")

    # ------------------------------------------------------ capacity plane

    def interner_host_bytes(self) -> int:
        """Host-byte estimate of the interner tables (capacity plane,
        ISSUE 19). Payload chars are a counter maintained at every
        growth point, so this is a cheap roll-up — never a table walk.
        Per-payload constant: tuple(2) 56 + str header 49 + list slot 8
        (the kind ints are shared small-int singletons)."""
        from ..utils import capacity as _cap
        n_pay = len(self._payloads)
        total = getattr(self, "_payload_chars", 0) + n_pay * (56 + 49 + 8)
        total += _cap.list_nbytes(len(self._client_idx))
        for m in self._client_idx:          # n_docs small dicts: ~1ms/10k
            total += _cap.dict_nbytes(len(m), _cap.INT_DICT_ENTRY_BYTES)
        total += _cap.dict_nbytes(len(self._prop_planes))
        # value interner: JSON-encoded key strings + value objects; a
        # flat per-entry constant (values are small scalars/strings)
        total += _cap.interner_nbytes(len(self._prop_values),
                                      80 * len(self._prop_values))
        total += _cap.dict_nbytes(
            len(getattr(self, "_props_pack_cache", ())),
            _cap.INT_DICT_ENTRY_BYTES)
        return int(total)


class TensorStringStore(StringOpInterner):
    #: Pallas dispatch policy — "auto": fused VMEM kernel on TPU for
    #: annotate-free stores with a compatible shape, XLA scan otherwise;
    #: "interpret": force the Pallas path through its interpreter (CPU
    #: parity tests); "off": always the XLA scan.
    pallas = "auto"

    def __init__(self, n_docs: int, capacity: int = 256, n_props: int = 4,
                 mesh=None):
        self.n_docs = n_docs
        self.capacity = capacity
        # multi-chip: a 1-D "docs" mesh shards the planes by doc row; every
        # apply/compact runs as a shard_map of the SAME kernels (zero
        # cross-chip collectives on the hot path — parallel/sharded.py)
        self.mesh = mesh
        if mesh is not None and n_docs % mesh.devices.size != 0:
            raise ValueError(f"n_docs {n_docs} not divisible by mesh size "
                             f"{mesh.devices.size}")
        # until the first annotate arrives the kernels run in the no-props
        # mode (all-zero planes are permutation-invariant; skipping their
        # movement saves ~35% HBM traffic on the hot path)
        self.state = StringState.create(n_docs, capacity, n_props)
        if mesh is not None:
            from ..parallel.sharded import shard_store_state
            self.state = shard_store_state(self.state, mesh)
        self._init_interner(n_docs, n_props)
        # serving-side intervals: anchors are (handle_op, handle_off) POINTS
        # — position-independent, stable under splits, tombstone-tolerant —
        # so op application never touches them (reference: local references;
        # the oracle's lazy-slide-at-resolve / re-anchor-at-zamboni split)
        self._intervals: List[Dict[str, tuple]] = [dict()
                                                   for _ in range(n_docs)]
        self._interval_counter = 0
        #: wire profile of the last columnar batch (None before the first)
        self.last_profile: Optional[tuple] = None
        #: rich payload wire form of the last batch: "plane"/"tab8"/"tab16"
        self.last_rich_wire: Optional[str] = None
        #: fused device→host gathers served (the read-path RTT budget)
        self.device_reads = 0
        # highest collaboration-window floor seen per doc (anchor slides
        # trigger at its advances, matching the oracle's zamboni timing)
        self._iv_min_seq = np.zeros((self.n_docs,), np.int64)
        # per-doc min-heap of uncompacted tombstone seqs, maintained ONLY
        # for interval-holding docs (seeded from the device planes when a
        # doc gains its first interval; pushed per remove; pruned as the
        # floor passes). Lets the apply path tell host-side whether a
        # window-floor advance actually dooms a tombstone — only then do
        # interval anchors need sliding at the crossing.
        self._iv_tombs: List[list] = [[] for _ in range(n_docs)]
        # rows currently holding intervals: the columnar hot path's "does
        # this batch need crossing bookkeeping at all" check must be O(1),
        # not a scan of n_docs dicts
        self._iv_docs: set = set()

    # --------------------------------------------------------- capacity plane

    def capacity_stats(self) -> dict:
        """Capacity-plane report fragment (ISSUE 19): host interner +
        interval bookkeeping, device plane bytes (sums to what
        ``jax.live_arrays()`` sees for this store's state)."""
        from ..utils import capacity as _cap
        n_iv = sum(len(d) for d in self._intervals)
        host = {
            "interner": self.interner_host_bytes(),
            # interval records: dict entry + 2 anchor tuples + props
            "intervals": (_cap.dict_nbytes(n_iv, 250)
                          + _cap.list_nbytes(self.n_docs) * 2
                          + _cap.ndarray_nbytes(self._iv_min_seq)),
        }
        return {"host": host,
                "device": {"state": _cap.device_nbytes(self.state)}}

    # ----------------------------------------------------------------- apply

    def apply_messages(self, messages) -> None:
        """messages: iterable of (doc, SequencedDocumentMessage) carrying
        merge-tree op contents (the ``mt`` dicts of SequenceClient).

        Documents holding intervals need anchor slides at the exact message
        where min_seq crosses a tombstone (the oracle slides per message as
        the window advances; sliding once per batch can pick a different
        target — e.g. a segment that was live at the crossing but tombstoned
        by batch end). The batch is split at such a crossing — and only
        there: the per-doc tombstone-seq heap tells us host-side whether an
        advance dooms anything, so interval-holding docs in an active
        collaboration (where MSN advances on nearly every message) still
        take large batched dispatches."""
        msgs = list(messages)
        iv_docs = self._iv_docs
        if not iv_docs:
            self._apply_batch(msgs)
            return
        group: list = []
        for doc, msg in msgs:
            group.append((doc, msg))
            if doc in iv_docs:
                if msg.min_seq > self._iv_min_seq[doc]:
                    self._iv_min_seq[doc] = msg.min_seq
                    if self._floor_dooms_tombstone(doc):
                        self._apply_batch(group)
                        group = []
                        self._slide_anchors_at_floor(doc)
                if msg.contents["mt"] == "remove":
                    heapq.heappush(self._iv_tombs[doc], msg.seq)
        if group:
            self._apply_batch(group)

    def _apply_batch(self, msgs) -> None:
        per_doc: Dict[int, list] = {}
        for doc, msg in msgs:
            recs = self._records_for(doc, msg)
            if recs:
                per_doc.setdefault(doc, []).extend(recs)
        if not per_doc:
            return
        # power-of-two op-axis buckets keep jit cache hits (static shapes)
        widest = max(len(v) for v in per_doc.values())
        o = 8
        while o < widest:
            o *= 2
        planes = {
            "kind": np.full((self.n_docs, o), int(OpKind.NOOP), np.int32),
            "a0": np.zeros((self.n_docs, o), np.int32),
            "a1": np.zeros((self.n_docs, o), np.int32),
            "a2": np.zeros((self.n_docs, o), np.int32),
            "seq": np.zeros((self.n_docs, o), np.int32),
            "client": np.zeros((self.n_docs, o), np.int32),
            "ref_seq": np.zeros((self.n_docs, o), np.int32),
        }
        for doc, recs in per_doc.items():
            for j, (k, x0, x1, x2, sq, cl, rs) in enumerate(recs):
                planes["kind"][doc, j] = k
                planes["a0"][doc, j] = x0
                planes["a1"][doc, j] = x1
                planes["a2"][doc, j] = x2
                planes["seq"][doc, j] = sq
                planes["client"][doc, j] = cl
                planes["ref_seq"][doc, j] = rs
        self._dispatch_apply(tuple(
            jnp.asarray(planes[k]) for k in
            ("kind", "a0", "a1", "a2", "seq", "client", "ref_seq")))

    def _tab_buffers(self, tab_n: int, T: int, P: int):
        """A (tab_a2, tab_len) pair of ``tab_n`` int32 buffers — reused
        from the pow2 pool when available (only the region past the live
        entries is re-zeroed; callers overwrite ``[:T+P]`` / ``[:T]``)."""
        pool = self._tab_pool.get(tab_n)
        if pool:
            tab_a2, tab_len = pool.pop()
            tab_a2[T + P:] = 0
            tab_len[T:] = 0
            return tab_a2, tab_len, True
        return (np.zeros((tab_n,), np.int32),
                np.zeros((tab_n,), np.int32), True)

    def _tab_release(self, pp: PrepackedPlanes) -> None:
        """Return a prepack's table buffers to the pow2 pool once the
        wire buffer has been built (np.concatenate copied them)."""
        if pp.pooled and pp.tab_a2 is not None:
            pool = self._tab_pool.setdefault(pp.tab_n, [])
            if len(pool) < 4:   # depth-bounded pipeline: tiny pool suffices
                pool.append((pp.tab_a2, pp.tab_len))
        pp.tab_a2 = pp.tab_len = None

    def _pack_payload_tables(self, rows, kind, a0, a1, text, texts, tidx,
                             props) -> PrepackedPlanes:
        """Build the payload/props side of a columnar apply's wire form:
        intern payloads, pack props, choose the rich wire mode, resolve
        insert lengths. Depends only on the RAW op planes — never on
        sequencing results — so the pipelined executor runs it on a pack
        worker concurrent with the previous wave's dispatch. Mutates the
        interner (payload handles allocate here): call in submission
        order, consume each result exactly once."""
        _t0 = time.perf_counter()
        pp = PrepackedPlanes()
        R, O = kind.shape
        ins = kind == int(OpKind.STR_INSERT)
        ann = kind == int(OpKind.STR_ANNOTATE)
        if ann.any() and props is None:
            raise ValueError("annotate slots require the props table")
        # interval anchors key by (payload handle, offset): two same-text
        # inserts in one doc must NOT share a handle or the anchor becomes
        # ambiguous (the per-message path mints one handle per op). A
        # batch touching any interval-holding row therefore mints per-op
        # handles and ships the resolved a2 plane; the dedup'd-table fast
        # wire stays reserved for interval-free batches.
        iv_handles = bool(self._iv_docs) and bool(ins.any()) \
            and not self._iv_docs.isdisjoint(
                np.asarray(rows).reshape(-1).tolist())
        pp.rich = not (texts is None and props is None) or iv_handles
        if not pp.rich:
            # broadcast payload: a2 is one scalar handle
            pp.a2_np = np.array([self._payload(_TEXT, text)], np.int32)
            pp.a1 = np.where(ins, len(text), a1)
            pp.prep_ms = (time.perf_counter() - _t0) * 1000
            return pp
        if tidx is not None:
            tidx = np.asarray(tidx, np.int32)
        packed_tab = np.zeros((0,), np.int32)
        if props is not None and ann.any():
            self._has_props = True
            packed_tab = np.empty((len(props),), np.int32)
            cache = self._props_pack_cache
            for j, p in enumerate(props):
                (key, value), = p.items()  # single-key by contract
                try:
                    packed = cache.get((key, value))
                except TypeError:   # unhashable value: intern directly
                    packed = None
                if packed is None:
                    packed = (self._prop_plane(key)
                              << PROP_HANDLE_BITS) \
                        | self._prop_handle(value)
                    try:
                        cache[(key, value)] = packed
                    except TypeError:
                        pass
                packed_tab[j] = packed
        if iv_handles:
            # per-op handle mint (anchor identity), resolved a2 plane
            pp.rich_mode = 1
            base_h = len(self._payloads)
            flat_ins = np.flatnonzero(ins.reshape(-1))
            if texts is not None:
                t_list = [texts[j] for j in
                          map(int, tidx.reshape(-1)[flat_ins])]
            else:
                t_list = [text] * len(flat_ins)
            self._payloads.extend((_TEXT, t) for t in t_list)
            self._payload_chars += sum(map(len, t_list))
            a2_np = np.zeros((R, O), np.int32)
            a2_np.reshape(-1)[flat_ins] = np.arange(
                base_h, base_h + len(flat_ins), dtype=np.int32)
            lens = np.zeros((R, O), np.int32)
            lens.reshape(-1)[flat_ins] = np.fromiter(
                map(len, t_list), np.int32, count=len(t_list))
            pp.a1 = np.where(ins, lens, a1)
            if len(packed_tab):
                a2_np[ann] = packed_tab[tidx[ann]]
            pp.a2_np = a2_np
            pp.prep_ms = (time.perf_counter() - _t0) * 1000
            return pp
        # ONE interner pass per unique payload/props entry: handles
        # resolve into small per-batch TABLES (texts first, packed
        # props after), and when the combined table fits a narrow
        # index the wire ships u8/u16 indices + the tables instead
        # of a resolved (R, O) i32 plane — the device gathers a2
        # and insert lengths itself (rich-pack vectorization
        # tentpole)
        if texts is not None:
            base_h = len(self._payloads)
            self._payloads.extend((_TEXT, t) for t in texts)
            handles_tab = np.arange(base_h, base_h + len(texts),
                                    dtype=np.int32)
            lens_tab = np.fromiter(map(len, texts), np.int32,
                                   count=len(texts))
            self._payload_chars += int(lens_tab.sum())
        elif ins.any():
            handles_tab = np.array([self._payload(_TEXT, text)],
                                   np.int32)
            lens_tab = np.array([len(text)], np.int32)
        else:
            handles_tab = np.zeros((1,), np.int32)
            lens_tab = np.zeros((1,), np.int32)
        T, P = len(handles_tab), len(packed_tab)
        if T + P <= 256:
            pp.rich_mode = 2
        elif T + P <= 65536:
            pp.rich_mode = 3
        else:
            pp.rich_mode = 1
        if pp.rich_mode != 1:
            # annotate indices shift past the text region; indices at
            # remove/NOOP slots are never validated NOR used (the
            # device zeroes a2 for those kinds and the gather clamps),
            # so they ride as-is
            tidx_eff = np.where(ann, tidx + T, tidx)
            if texts is None and ins.any():
                # broadcast-insert + props form: tidx only indexes the
                # props table; inserts all take table entry 0
                tidx_eff = np.where(ins, 0, tidx_eff)
            pp.tidx_eff = tidx_eff
            pp.tab_n = max(8, 1 << (T + P - 1).bit_length())
            pp.tab_a2, pp.tab_len, pp.pooled = \
                self._tab_buffers(pp.tab_n, T, P)
            pp.tab_a2[:T] = handles_tab
            pp.tab_a2[T:T + P] = packed_tab
            pp.tab_len[:T] = lens_tab
            # wire a1 for inserts is a placeholder (= a0, so spans stay
            # 0 and positions stay narrow); the device substitutes the
            # table length — the host never builds the lens plane
            pp.a1 = np.where(ins, a0, a1)
        else:               # huge tables: resolved i32 a2 plane
            a2_np = np.zeros((R, O), np.int32)
            a1_out = a1
            if texts is not None:
                a2_np[ins] = handles_tab[tidx[ins]]
                a1_out = np.where(ins, lens_tab.take(tidx, mode="clip"),
                                  a1)
            elif ins.any():
                a2_np[ins] = handles_tab[0]
                a1_out = np.where(ins, lens_tab[0], a1)
            if P:
                a2_np[ann] = packed_tab[tidx[ann]]
            pp.a2_np = a2_np
            pp.a1 = a1_out
        pp.prep_ms = (time.perf_counter() - _t0) * 1000
        return pp

    def prepack_planes(self, rows, kind, a0, a1, text: str = "",
                       texts=None, tidx=None,
                       props=None) -> Optional[PrepackedPlanes]:
        """Pipelined-ingest hook: run the seq-independent pack work for a
        wave AHEAD of its sequencing (concurrent with the previous wave's
        dispatch) and hand the result to ``apply_planes(prepacked=...)``.

        Returns ``None`` when the batch touches interval-holding rows:
        that path mints one payload handle per ACKED op (anchor
        identity), which depends on post-sequencing nack knowledge — the
        caller must fall back to the inline pack (and, in a pipeline,
        barrier until this wave's dispatch completes so handle order
        stays serial). The raw ``kind`` plane is assumed all-acked;
        nacked slots only affect unused table entries (exactly as the
        inline path, which interns whole tables regardless of nacks)."""
        kind = np.asarray(kind, np.int32)
        ins = kind == int(OpKind.STR_INSERT)
        if bool(self._iv_docs) and bool(ins.any()) \
                and not self._iv_docs.isdisjoint(
                    np.asarray(rows).reshape(-1).tolist()):
            return None
        return self._pack_payload_tables(
            np.asarray(rows), kind, np.asarray(a0, np.int32),
            np.asarray(a1, np.int32), text, texts, tidx, props)

    def apply_planes(self, rows, kind, a0, a1, seq_base, client_id, ref_seq,
                     text: str = "", min_seq=None, texts=None, tidx=None,
                     props=None, min_ops=None, prepacked=None) -> None:
        """Columnar apply: dense (R, O) already-sequenced op planes for the
        subset of doc rows ``rows`` (R,) — the ingest hot path (no per-op
        Python objects anywhere). Ops per doc apply in column order (the
        sequencer's per-doc total order); NOOP slots (nacked ops) are
        skipped and consumed no seq, so per-op seqs are reconstructed ON
        DEVICE from the per-row ``seq_base`` (the doc's seq before the
        batch).

        Payloads: either the broadcast ``text`` (every insert inserts the
        same run — the typing-storm shape) or per-op payloads via
        ``texts`` (a payload table) + ``tidx`` ((R, O) int32 indices into
        it) — the distinct-payload shape real text produces. Insert a1 is
        derived from the payload either way.

        Annotates (kind == STR_ANNOTATE) are admitted when ``props`` (a
        table of SINGLE-key {key: value} dicts, indexed by ``tidx``) is
        given: one columnar slot = one (key, value) range annotate =
        one sequence number. Multi-key annotates and insert-with-props
        expand to several same-seq records and must go through
        ``apply_messages``.

        ``min_seq`` (n_docs,) fuses zamboni into the same dispatch (the
        apply+compact single-HBM-round-trip configuration); if any doc in
        the store holds intervals, compaction falls back to ``compact``
        (which re-anchors before dropping tombstones).

        Docs holding intervals ride this path too: pass ``min_ops`` — the
        (R, O) per-op min_seq plane the sequencer stamped — and the batch
        is split at the exact column where a doc's window floor crosses a
        pending tombstone (the oracle slides refs per message as the
        window advances; sliding once per batch can pick a different
        target). Between segments the doomed docs' anchors re-anchor off
        the device state AT the crossing, via one fused gather for every
        crossing doc. Without ``min_ops`` the floor is assumed not to
        advance inside the batch (removes still feed the tombstone heaps,
        so a later ``advance_min_seq``/``compact`` slides correctly)."""
        _t0 = time.perf_counter()
        rows = np.ascontiguousarray(rows, np.int32)
        R, O = kind.shape
        if len(np.unique(rows)) != R:
            raise ValueError("duplicate rows in columnar batch (the device "
                             "scatter would silently drop ops)")
        kind = np.asarray(kind, np.int32)
        ins = kind == int(OpKind.STR_INSERT)
        a0 = np.asarray(a0, np.int32)
        a1 = np.asarray(a1, np.int32)
        # payload/props side of the pack: either handed in by the
        # pipelined executor's pack worker (``prepacked``, built
        # concurrent with the previous wave's dispatch) or built inline
        # right here — identical code either way (_pack_payload_tables)
        pp = prepacked
        if pp is None:
            pp = self._pack_payload_tables(rows, kind, a0, a1, text,
                                           texts, tidx, props)
        rich = pp.rich
        rich_mode = pp.rich_mode
        a2_np = pp.a2_np
        tab_a2, tab_len, tab_n = pp.tab_a2, pp.tab_len, pp.tab_n
        tidx_eff = pp.tidx_eff
        a1 = pp.a1

        # vectorized client interning. Fast path: one writer per doc row in
        # this batch (the common live-collaboration window) — R dict hits,
        # no materialized (R·O) key array — with a one-entry cache: steady
        # serving re-presents the SAME (rows, client) pairing every batch,
        # which a memcmp detects without touching the dicts. General path:
        # one dict hit per UNIQUE (row, client) pair via a packed int64 key
        # (np.unique on a 1-D int key is ~10× faster than axis=0 row
        # dedup); nacked/NOOP slots never mint an index there.
        valid = kind != int(OpKind.NOOP)
        cidx = np.zeros((R, O), np.int32)
        cid = np.asarray(client_id, np.int32)
        cmax = 0
        if (cid == cid[:, :1]).all():
            cid0 = np.ascontiguousarray(cid[:, 0])
            rkey, ckey = rows.tobytes(), cid0.tobytes()
            cached = self._cidx_cache
            rows_any = valid.any(axis=1)
            all_rows_valid = bool(rows_any.all())
            if cached is not None and all_rows_valid \
                    and cached[0] == rkey and cached[1] == ckey:
                lut = cached[2]
            else:
                # mint only for rows with at least one acked op (an
                # all-NOOP row must not consume one of the doc's
                # MAX_CLIENTS slots — and must match what a log rebuild
                # would intern)
                lut = np.zeros(R, np.int32)
                mint = self._client
                rows_l, cid_l = rows.tolist(), cid0.tolist()
                for i in map(int, np.flatnonzero(rows_any)):
                    lut[i] = mint(rows_l[i], cid_l[i])
                if all_rows_valid:
                    self._cidx_cache = (rkey, ckey, lut)
            cidx[:] = lut[:, None]
            cmax = int(lut.max(initial=0))
        elif valid.any():
            rr = np.broadcast_to(rows[:, None], (R, O))[valid]
            cc = cid.astype(np.int64)[valid]
            key = (rr.astype(np.int64) << 32) | (cc & 0xFFFFFFFF)
            uniq, inv = np.unique(key, return_inverse=True)
            lut = np.array(
                [self._client(int(k >> 32), int(np.int32(k & 0xFFFFFFFF)))
                 for k in uniq], np.int32)
            cidx[valid] = lut[inv]
            cmax = int(lut.max(initial=0))

        # unsigned u16 packing would alias a (malformed) negative position
        # to ~65535 — minima force such inputs onto the sign-preserving
        # wide path, where they behave exactly like the per-op path
        narrow = int(a0.max(initial=0)) < 32767 and \
            int(a1.max(initial=0)) < 32767 and \
            int(a0.min(initial=0)) >= 0 and int(a1.min(initial=0)) >= 0
        seq_base = np.asarray(seq_base, np.int32)
        seq = seq_base[:, None] + np.cumsum(valid, axis=1, dtype=np.int32)
        lag = np.subtract(seq, np.asarray(ref_seq, np.int32))
        np.maximum(lag, 1, out=lag)
        ref_wide = bool((lag > 65535).any())
        use_pallas, tile, interpret = self._pallas_choice()
        scatter_rows = not (R == self.n_docs
                            and np.array_equal(rows, np.arange(R)))
        fuse = min_seq is not None and not self._iv_docs
        ms = np.asarray(min_seq, np.int32) if fuse \
            else np.zeros((1,), np.int32)
        # tightest profile first: 5 B/op when spans, lags and client
        # indexes all fit a byte (the live-collaboration common case —
        # see _columnar_unpack_jit on why wire bytes are the ceiling).
        # (kind-set membership via compares, not np.isin — isin costs ~8 ms
        # at 655k ops for the same answer)
        span = np.where(ins, a1, a1 - a0) if rich_mode < 2 \
            else np.where(ins, 0, a1 - a0)
        kinds_ok = bool(((kind >= 0) & ((kind <= int(OpKind.STR_ANNOTATE))
                                        | ~valid)).all())
        compact8 = bool(
            narrow and not ref_wide and kinds_ok
            and cmax < 64
            and int(lag.max(initial=0)) < 256
            and int(span.max(initial=0)) < 256
            and int(span.min(initial=0)) >= 0)
        # observability: which wire profile this batch took (head encoding,
        # position width, payload form) — tests pin each branch by name;
        # the rich payload's wire form (plane vs table) rides separately
        self.last_profile = (
            "compact8" if compact8 else
            "ref_wide" if ref_wide else "lag16",
            "pos16" if narrow else "pos32",
            "rich" if rich else "broadcast")
        self.last_rich_wire = (None if not rich else
                               {1: "plane", 2: "tab8", 3: "tab16"}
                               [rich_mode])

        # interval crossing scan: split the batch at every column where a
        # doc's window floor crosses a pending tombstone (mirrors the
        # apply_messages per-message bookkeeping; mutates the heaps/floors)
        segments = [(0, O, ())]
        if self._iv_docs:
            if min_ops is not None:
                min_ops = np.asarray(min_ops)
            splits = self._interval_scan(rows, kind, seq, min_ops)
            if splits:
                segs, prev = [], 0
                for b in sorted(splits):
                    segs.append((prev, b, splits[b]))
                    prev = b
                if prev < O:
                    segs.append((prev, O, ()))
                segments = segs

        # word-pack EVERYTHING into one int32 buffer: over a
        # tunnel-attached device each transfer pays the link round-trip,
        # so the whole batch (planes + rows + seq bases + fused min_seq)
        # rides ONE host→device copy at ~8 B/op (see _columnar_unpack_jit)
        def seg_u8(arr):
            b = np.ascontiguousarray(arr, np.uint8).reshape(-1)
            if len(b) % 4:
                b = np.concatenate([b, np.zeros((-len(b)) % 4, np.uint8)])
            return b.view("<i4")

        def seg_u16(arr):
            b = np.ascontiguousarray(arr, "<u2").reshape(-1)
            if len(b) % 2:
                b = np.concatenate([b, np.zeros(1, "<u2")])
            return b.view("<i4")

        seg_pos = (lambda a: np.ascontiguousarray(a, "<i4").reshape(-1)) \
            if not narrow else seg_u16

        def pad_cols(arr, c0, c1, wp, fill=0):
            """Column slice padded to the wp bucket (NOOP-filled pads
            consume no seq and touch no state)."""
            w = c1 - c0
            if c0 == 0 and c1 == O and wp == O:
                return arr
            out = np.full((R, wp), fill, np.int32)
            out[:, :w] = arr[:, c0:c1]
            return out

        ref_i32 = None
        if ref_wide:
            ref_i32 = np.ascontiguousarray(ref_seq, "<i4")

        pack_ms = 0.0
        dispatch_ms = 0.0
        _t_prep = time.perf_counter()
        for si, (c0, c1, slides) in enumerate(segments):
            _t_s0 = time.perf_counter()
            last_seg = si == len(segments) - 1
            fuse_seg = fuse and last_seg
            ms_seg = ms if fuse_seg else np.zeros((1,), np.int32)
            w = c1 - c0
            # power-of-two column buckets keep the jit cache warm when a
            # crossing splits the batch (the no-split common case keeps
            # the exact original shape)
            wp = O if w == O else max(8, 1 << (w - 1).bit_length())
            k_s = pad_cols(kind, c0, c1, wp, fill=int(OpKind.NOOP))
            a0_s = pad_cols(a0, c0, c1, wp)
            lag_s = pad_cols(lag, c0, c1, wp, fill=1)
            cidx_s = pad_cols(cidx, c0, c1, wp)
            base_s = seq_base if c0 == 0 else \
                np.ascontiguousarray(seq[:, c0 - 1])
            if compact8:
                span_s = pad_cols(span, c0, c1, wp)
                kc = np.where(k_s == int(OpKind.NOOP), 3, k_s) \
                    | (cidx_s << 2)
                head = [seg_u8(kc), seg_u16(a0_s), seg_u8(span_s),
                        seg_u8(lag_s)]
            elif ref_wide:
                head = [seg_u8(k_s), seg_u8(cidx_s), seg_pos(a0_s),
                        seg_pos(pad_cols(a1, c0, c1, wp)),
                        pad_cols(ref_i32, c0, c1, wp).reshape(-1)
                        .astype("<i4", copy=False)]
            else:  # ship the (u16) lag; device reconstructs ref=seq-lag
                head = [seg_u8(k_s), seg_u8(cidx_s), seg_pos(a0_s),
                        seg_pos(pad_cols(a1, c0, c1, wp)),
                        seg_u16(lag_s)]
            if rich_mode >= 2:
                tail = [(seg_u8 if rich_mode == 2 else seg_u16)(
                            pad_cols(tidx_eff, c0, c1, wp)),
                        tab_a2.astype("<i4", copy=False),
                        tab_len.astype("<i4", copy=False)]
            elif rich_mode == 1:
                tail = [np.ascontiguousarray(
                    pad_cols(a2_np, c0, c1, wp), "<i4").reshape(-1)]
            else:
                tail = [a2_np.astype("<i4", copy=False)]
            buf = np.concatenate(head + tail + [
                base_s.astype("<i4", copy=False),
                rows.astype("<i4", copy=False),
                ms_seg.astype("<i4", copy=False),
            ])
            _t_pack = time.perf_counter()
            planes, ms_dev = _columnar_unpack_jit(
                jnp.asarray(buf), R=R, O=wp,
                pos_wide=not narrow, ref_wide=ref_wide, rich=rich_mode,
                n_docs=self.n_docs, fuse_compact=fuse_seg,
                scatter_rows=scatter_rows, compact8=compact8,
                tab_n=tab_n)
            if self.mesh is not None:
                # planes are (n_docs, O) either way: subset batches
                # scattered by the unpack, full-store batches already in
                # row order
                from ..parallel.sharded import sharded_merge
                fn = sharded_merge(self.mesh, use_pallas, tile, interpret,
                                   self._has_props, fuse_seg)
                self.state = fn(self.state, planes, ms_dev) if fuse_seg \
                    else fn(self.state, planes)
            else:
                self.state = _columnar_merge_jit(
                    self.state, planes, ms_dev, use_pallas=use_pallas,
                    tile=tile, interpret=interpret,
                    with_props=self._has_props, fuse_compact=fuse_seg)
            _t_done = time.perf_counter()
            pack_ms += (_t_pack - _t_s0) * 1000
            dispatch_ms += (_t_done - _t_pack) * 1000
            if slides:
                # re-anchor the crossing docs off the device state AS OF
                # this segment's end — one fused gather for all of them
                # (the gather also drains the dispatch pipeline, so the
                # planes it returns include this segment's ops)
                self._slide_docs(slides)
        self._tab_release(pp)
        #: host-packing vs device-dispatch wall per columnar apply — the
        #: breakdown behind the serving throughput number (dispatches are
        #: async; device time is measured by the caller's end sync).
        #: ``prepack_ms`` is the payload/table build wall: when the wave
        #: came through the pipelined executor that work ran OFF the
        #: critical path (concurrent with the previous wave's dispatch)
        #: and pack_ms counts only the inline remainder.
        self.last_apply_stats = {
            "pack_ms": (_t_prep - _t0) * 1000 + pack_ms,
            "prepack_ms": pp.prep_ms if prepacked is not None else 0.0,
            "dispatch_ms": dispatch_ms,
            "segments": len(segments),
        }
        _note_dispatch("columnar", dispatch_ms)
        if min_seq is not None and not fuse:
            self.compact(np.asarray(min_seq))

    def _pallas_choice(self):
        """(use_pallas, tile, interpret) for this store's dispatch policy.
        Annotate-bearing stores run the props specialization (K property
        planes in VMEM) at a halved tile — the extra planes eat VMEM.
        On a mesh, the tile must divide each shard's LOCAL doc block."""
        local_docs = self.n_docs if self.mesh is None \
            else self.n_docs // self.mesh.devices.size
        tile = pallas_tile_for(local_docs, self.capacity)
        mode = self.pallas
        use_pallas = (tile is not None and
                      (mode == "interpret" or
                       (mode == "auto" and
                        jax.default_backend() == "tpu")))
        if use_pallas and self._has_props and tile > 64:
            # props mode carries K extra planes + their temporaries in
            # VMEM: T=64 at S=384/K=4 fits (and measures fastest: 6.98M
            # conflict-ops/s on v5e); T=128 exceeds the 16M scoped budget
            for smaller in (64, 32, 16, 8):
                if smaller <= tile and local_docs % smaller == 0:
                    tile = smaller
                    break
        # VMEM budget scales with tile×capacity. Calibrated from the
        # compiler: T=128 at S=512 allocates 19.54M scoped (≈300 B per
        # tile×slot incl. temporaries) vs the 16M limit, while T=128 at
        # S=384 (≈14.7M) fits. Halve the tile until under budget.
        while (tile is not None and tile > 8
               and tile * self.capacity * 300 > 15_500_000):
            nxt = tile // 2
            if local_docs % nxt != 0:
                break
            tile = nxt
        if use_pallas and tile is not None \
                and tile * self.capacity * 300 > 15_500_000:
            # no smaller dividing tile fits the scoped-VMEM budget (odd
            # doc factors, or large capacity even at T=8): an over-budget
            # Pallas launch fails compilation on a real TPU — take the
            # XLA scan path instead
            use_pallas = False
        return use_pallas, (tile if tile is not None else 8), \
            (mode == "interpret")

    def _dispatch_apply(self, op_planes: tuple) -> None:
        """One device apply of dense (D, O) op planes, on the fused Pallas
        kernel when eligible (VERDICT r1 #1: the serving path runs the same
        kernel the headline measures), else the XLA scan."""
        use_pallas, tile, interpret = self._pallas_choice()
        t0 = time.perf_counter()
        if self.mesh is not None:
            from ..parallel.sharded import sharded_merge
            self.state = sharded_merge(
                self.mesh, use_pallas, tile, interpret, self._has_props,
                fuse_compact=False)(self.state, tuple(op_planes))
        elif use_pallas:
            self.state = _apply_pallas_jit(
                self.state, *op_planes, tile=tile, interpret=interpret,
                with_props=self._has_props)
        else:
            self.state = apply_string_batch_jit(
                self.state, *op_planes, with_props=self._has_props)
        _note_dispatch("pallas" if use_pallas else "batch",
                       (time.perf_counter() - t0) * 1000)

    def compact(self, min_seq) -> None:
        """Zamboni: free tombstones below the collaboration window."""
        # host array first: np.asarray on a device array is a device→host
        # read that would sync the whole dispatch pipeline (tunnel RTT)
        ms_host = np.full((self.n_docs,), int(min_seq), np.int32) \
            if np.isscalar(min_seq) else np.asarray(min_seq, np.int32)
        ms = jnp.asarray(ms_host)
        self._reanchor_for_compact(ms_host)
        if self.mesh is not None:
            from ..parallel.sharded import sharded_compact
            self.state = sharded_compact(self.mesh, self._has_props)(
                self.state, ms)
        else:
            self.state = compact_string_state_jit(
                self.state, ms, with_props=self._has_props)
        for doc in self._iv_docs:
            self._prune_tombs(doc, int(ms_host[doc]))

    # ----------------------------------------------------------------- reads

    def _pull_doc(self, doc: int):
        """One fused device→host gather of a doc's read planes (each
        separate plane pull pays a full device round-trip — ruinous over a
        tunnel link): (removed_seq, handle_op, handle_off, length, seq)
        trimmed to the doc's slot count. ``device_reads`` counts these —
        the read path's round-trip budget is asserted from it."""
        self.device_reads = getattr(self, "device_reads", 0) + 1
        REGISTRY.inc("device_reads")
        # (getattr: restore() builds stores via __new__)
        arr = np.asarray(_gather_doc_jit(self.state, doc))
        n = int(arr[5, 0])
        return tuple(arr[i, :n] for i in range(5))

    def read_text(self, doc: int) -> str:
        rem, hop, hoff, length, _ = self._pull_doc(doc)
        parts = []
        for i in range(len(rem)):
            if rem[i] != NOT_REMOVED:
                continue
            kind, text = self._payloads[hop[i]]
            if kind == _TEXT:
                parts.append(text[hoff[i]:hoff[i] + length[i]])
        return "".join(parts)

    def visible_length(self, doc: int) -> int:
        rem, _, _, length, _ = self._pull_doc(doc)
        return int(length[rem == NOT_REMOVED].sum())

    def visible_lengths(self) -> np.ndarray:
        """(D,) visible lengths of EVERY doc in one device round-trip (a
        per-doc loop pays D tunnel RTTs)."""
        return np.asarray(_visible_lengths_jit(self.state))

    @staticmethod
    def _slot_in_planes(rem, length, pos: int) -> int:
        """Slot index holding visible position ``pos`` in pulled planes
        (skip tombstones, accumulate live lengths) — the ONE visible-
        position resolver shared by every read."""
        at = 0
        for i in range(len(rem)):
            if rem[i] != NOT_REMOVED:
                continue
            if at <= pos < at + length[i]:
                return i
            at += length[i]
        raise IndexError(f"position {pos} beyond visible length {at}")

    def _slot_at(self, doc: int, pos: int) -> int:
        rem, _, _, length, _ = self._pull_doc(doc)
        return self._slot_in_planes(rem, length, pos)

    def seq_at(self, doc: int, pos: int) -> int:
        """Insert seq of the slot holding visible position ``pos`` — the
        attribution key (reference: merge-tree segments carry their seq;
        the device seq plane stores the same)."""
        rem, _, _, length, seqp = self._pull_doc(doc)
        return int(seqp[self._slot_in_planes(rem, length, pos)])

    def get_properties(self, doc: int, pos: int) -> dict:
        """Properties of the character at visible position pos (reference:
        ``SharedString.getPropertiesAtPosition``)."""
        i = self._slot_at(doc, pos)
        pv = np.asarray(self.state.prop_val[doc][i])
        return {key: self._prop_values.value(int(pv[plane]))
                for key, plane in self._prop_planes.items()
                if pv[plane] != 0}

    # -------------------------------------------------------- intervals
    # Anchored ranges over the served text (reference: IntervalCollection /
    # SequenceInterval with SlideOnRemove endpoints).

    def _doc_slots(self, doc: int):
        """(handle_op, handle_off, length, live) of active slots, host-side."""
        rem, hop, hoff, length, _ = self._pull_doc(doc)
        return hop, hoff, length, rem == NOT_REMOVED

    def _anchor_at(self, doc: int, pos: int):
        """Anchor of the visible character at pos (doc end → last visible
        char; empty doc → detached None), mirroring the oracle's _anchor."""
        hop, hoff, length, live = self._doc_slots(doc)
        at = 0
        last = None
        for i in range(len(hop)):
            if not live[i]:
                continue
            if at <= pos < at + length[i]:
                return (int(hop[i]), int(hoff[i]) + (pos - at))
            at += length[i]
            last = (int(hop[i]), int(hoff[i]) + int(length[i]) - 1)
        return last  # pos at/after doc end → last char; None if empty

    def _anchor_position(self, doc: int, anchor, slots=None) -> int:
        """Resolve an anchor with SLIDE semantics: a tombstoned anchor
        resolves to the nearest following live position (the live prefix at
        its slot), like the oracle's get_position. ``slots`` lets a caller
        resolving many anchors fetch the doc's planes once."""
        if anchor is None:
            return 0  # detached parks at document start
        h, off = anchor
        hop, hoff, length, live = slots if slots is not None \
            else self._doc_slots(doc)
        at = 0
        for i in range(len(hop)):
            if hop[i] == h and hoff[i] <= off < hoff[i] + length[i]:
                return at + (off - int(hoff[i])) if live[i] else at
            if live[i]:
                at += length[i]
        return at  # anchor's slot gone (shouldn't outlive compact re-anchor)

    def _floor_dooms_tombstone(self, doc: int) -> bool:
        """Does the current window floor reach a pending tombstone (so
        anchors must slide before more ops land)?"""
        tombs = self._iv_tombs[doc]
        return bool(tombs) and tombs[0] <= self._iv_min_seq[doc]

    def _slide_anchors_at_floor(self, doc: int) -> None:
        """Slide anchors off slots doomed by the current floor, then drop
        those tombstones from the heap (an already-slid tombstone never
        needs another slide)."""
        self._reanchor_for_compact(self._iv_min_seq, only_doc=doc)
        self._prune_tombs(doc, int(self._iv_min_seq[doc]))

    def _prune_tombs(self, doc: int, floor: int) -> None:
        tombs = self._iv_tombs[doc]
        while tombs and tombs[0] <= floor:
            heapq.heappop(tombs)

    def _seed_tombs(self, doc: int) -> None:
        """Rebuild the doc's tombstone heap from the device planes (on the
        first interval, or after restore): any resident removed_seq above
        the floor is a tombstone a future floor advance could doom."""
        st = self.state
        n = int(st.count[doc])
        removed = np.asarray(st.removed_seq[doc][:n])
        floor = self._iv_min_seq[doc]
        tombs = [int(s) for s in removed[removed != NOT_REMOVED]
                 if s > floor]
        heapq.heapify(tombs)
        self._iv_tombs[doc] = tombs

    def add_intervals_bulk(self, spans: Dict[int, list]
                           ) -> Dict[int, List[str]]:
        """Anchor many intervals across many docs with ONE fused device
        gather: ``spans`` maps doc row → [(start, end, props)].
        ``add_interval`` pays ≥2 device round trips per call (tomb seed +
        anchor pulls) — ruinous over a tunnel link for mass setup (e.g.
        loading an annotated corpus); this path pulls every target row's
        read planes in one dispatch and anchors host-side."""
        rows = np.asarray(sorted(spans), np.int32)
        if not len(rows):
            return {}
        n = len(rows)
        p2 = 1 << (n - 1).bit_length()
        rows_p = np.concatenate([rows, np.full(p2 - n, rows[0],
                                               np.int32)])
        g = [np.asarray(x)[:n] for x in
             _gather_rows_jit(self.state, jnp.asarray(rows_p))]
        self.device_reads = getattr(self, "device_reads", 0) + 1
        REGISTRY.inc("device_reads")
        removed_g, length_g = g[2], g[4]
        hop_g, hoff_g, count_g = g[5], g[6], g[8]
        out: Dict[int, List[str]] = {}
        for j, row in enumerate(map(int, rows)):
            cnt = int(count_g[j])
            removed = removed_g[j, :cnt]
            hop, hoff = hop_g[j, :cnt], hoff_g[j, :cnt]
            length = length_g[j, :cnt]
            live = removed == NOT_REMOVED
            if not self._intervals[row]:
                # seed tombs from the pulled planes (no extra read)
                floor = self._iv_min_seq[row]
                tombs = [int(s) for s in removed[removed != NOT_REMOVED]
                         if s > floor]
                heapq.heapify(tombs)
                self._iv_tombs[row] = tombs

            def anchor(pos: int):
                at = 0
                last = None
                for i in range(cnt):
                    if not live[i]:
                        continue
                    if at <= pos < at + length[i]:
                        return (int(hop[i]), int(hoff[i]) + (pos - at))
                    at += int(length[i])
                    last = (int(hop[i]),
                            int(hoff[i]) + int(length[i]) - 1)
                return last

            ids = []
            for start, end, props in spans[row]:
                self._interval_counter += 1
                iid = f"iv{self._interval_counter}"
                self._intervals[row][iid] = (anchor(start), anchor(end),
                                             dict(props or {}))
                ids.append(iid)
            self._iv_docs.add(row)
            out[row] = ids
        return out

    def add_interval(self, doc: int, start: int, end: int,
                     props: Optional[dict] = None) -> str:
        if not self._intervals[doc]:
            self._seed_tombs(doc)  # bookkeeping starts at the first interval
        self._interval_counter += 1
        iid = f"iv{self._interval_counter}"
        self._intervals[doc][iid] = (self._anchor_at(doc, start),
                                     self._anchor_at(doc, end),
                                     dict(props or {}))
        self._iv_docs.add(doc)
        return iid

    def remove_interval(self, doc: int, iid: str) -> None:
        del self._intervals[doc][iid]
        if not self._intervals[doc]:
            self._iv_docs.discard(doc)

    def interval_endpoints(self, doc: int, iid: str):
        a, b, _props = self._intervals[doc][iid]
        slots = self._doc_slots(doc)
        return (self._anchor_position(doc, a, slots),
                self._anchor_position(doc, b, slots))

    def intervals(self, doc: int) -> dict:
        slots = self._doc_slots(doc)
        return {iid: (self._anchor_position(doc, a, slots),
                      self._anchor_position(doc, b, slots), dict(props))
                for iid, (a, b, props) in self._intervals[doc].items()}

    def advance_min_seq(self, doc: int, min_seq: int) -> None:
        """Window-floor advance that arrived outside the op stream (NOOP
        heartbeats at the serving engine): slide this doc's anchors now, at
        the crossing, exactly as an in-stream advance would."""
        if not self._intervals[doc] or min_seq <= self._iv_min_seq[doc]:
            return
        self._iv_min_seq[doc] = min_seq
        if self._floor_dooms_tombstone(doc):
            self._slide_anchors_at_floor(doc)

    def _interval_scan(self, rows, kind, seq, min_ops):
        """Host-side crossing scan for a columnar batch (mirrors
        ``apply_messages``'s per-message bookkeeping, vectorized): walk
        each interval-holding row's op columns, advance the doc's window
        floor from the per-op ``min_ops`` plane, and whenever the floor
        crosses a pending tombstone record a segment boundary AFTER that
        column (the crossing op itself lands before the slide, exactly as
        the oracle applies the crossing message before sliding). Removes
        feed the tombstone heap AFTER the crossing check (a remove's own
        seq can never be ≤ the floor it ships with).

        Returns {boundary_col: ((doc, floor_at_crossing), ...)}; mutates
        the heaps and floors. With ``min_ops=None`` only the heaps are
        fed (floor advances arrive via advance_min_seq/compact)."""
        splits: Dict[int, list] = {}
        rem_k = int(OpKind.STR_REMOVE)
        noop_k = int(OpKind.NOOP)
        iv = self._iv_docs
        for i, d in enumerate(map(int, rows)):
            if d not in iv:
                continue
            krow = kind[i]
            rem_mask = krow == rem_k
            if min_ops is None:
                tombs = self._iv_tombs[d]
                for j in map(int, np.flatnonzero(rem_mask)):
                    heapq.heappush(tombs, int(seq[i, j]))
                continue
            mrow = min_ops[i]
            floor = self._iv_min_seq[d]
            cand = np.flatnonzero(rem_mask
                                  | ((krow != noop_k) & (mrow > floor)))
            if not len(cand):
                continue
            tombs = self._iv_tombs[d]
            for j in map(int, cand):
                m = int(mrow[j])
                if m > floor:
                    floor = m
                    if tombs and tombs[0] <= floor:
                        splits.setdefault(j + 1, []).append((d, floor))
                        while tombs and tombs[0] <= floor:
                            heapq.heappop(tombs)
                if rem_mask[j]:
                    heapq.heappush(tombs, int(seq[i, j]))
            self._iv_min_seq[d] = floor
        return {b: tuple(v) for b, v in splits.items()}

    def _slide_docs(self, pairs) -> None:
        """Re-anchor a set of (doc, floor) crossings off the CURRENT
        device state with ONE fused gather (a per-doc plane pull pays a
        tunnel RTT each — this is the batched device apply's slide step,
        so it must not undo the columnar path's round-trip win)."""
        if not pairs:
            return
        docs = np.asarray([d for d, _ in pairs], np.int32)
        n = len(docs)
        p2 = 1 << (n - 1).bit_length() if n > 1 else 1
        rows_p = np.concatenate([docs, np.full(p2 - n, docs[0], np.int32)])
        g = [np.asarray(x)[:n] for x in
             _gather_rows_jit(self.state, jnp.asarray(rows_p))]
        self.device_reads = getattr(self, "device_reads", 0) + 1
        REGISTRY.inc("device_reads")
        removed_g, length_g = g[2], g[4]
        hop_g, hoff_g, count_g = g[5], g[6], g[8]
        for j, (d, floor) in enumerate(pairs):
            cnt = int(count_g[j])
            self._reanchor_arrays(d, floor, removed_g[j, :cnt],
                                  hop_g[j, :cnt], hoff_g[j, :cnt],
                                  length_g[j, :cnt])

    def _reanchor_arrays(self, doc: int, floor: int, removed, hop, hoff,
                         length) -> None:
        """Slide this doc's anchors off slots doomed at ``floor`` using
        already-pulled planes: to the first following live char, else the
        last preceding live char, else detach (oracle _slide_refs
        rules). Locates are vectorized compares, not Python slot walks."""
        doomed = removed <= floor
        if not doomed.any():
            return
        live_idx = np.flatnonzero(removed == NOT_REMOVED)
        hi = hoff + length

        def slide(i):
            k = np.searchsorted(live_idx, i + 1)
            if k < len(live_idx):           # first following live char
                j = live_idx[k]
                return (int(hop[j]), int(hoff[j]))
            k = np.searchsorted(live_idx, i) - 1
            if k >= 0:                      # last preceding live char
                j = live_idx[k]
                return (int(hop[j]), int(hi[j]) - 1)
            return None                     # no live text: detach

        for iid, (a, b, props) in list(self._intervals[doc].items()):
            new = []
            for anchor in (a, b):
                if anchor is not None:
                    h, off = anchor
                    hit = np.flatnonzero((hop == h) & (hoff <= off)
                                         & (off < hi))
                    if len(hit) and doomed[hit[0]]:
                        anchor = slide(int(hit[0]))
                new.append(anchor)
            self._intervals[doc][iid] = (new[0], new[1], props)

    def _reanchor_for_compact(self, min_seq: np.ndarray,
                              only_doc: Optional[int] = None) -> None:
        """Before zamboni drops tombstones at or below min_seq, move anchors
        off doomed slots (oracle _slide_refs rules). Only docs whose
        tombstone heap is actually doomed by the new floor pull device
        planes — and all of them share ONE fused gather."""
        docs = self._iv_docs if only_doc is None else (only_doc,)
        pairs = []
        for doc in docs:
            if not self._intervals[doc]:
                continue
            floor = int(min_seq[doc])
            tombs = self._iv_tombs[doc]
            if tombs and tombs[0] <= floor:
                pairs.append((doc, floor))
        self._slide_docs(pairs)

    # ------------------------------------------------- overflow recovery

    def adopt_doc(self, row: int, tmp: "TensorStringStore",
                  src_row: int = 0) -> None:
        """Adopt row ``src_row`` of ``tmp``'s rebuilt state into ``row`` —
        the re-upload step of the overflow escape hatch (SURVEY.md §7
        risk (b)): payload handles re-intern into this store's table, the
        per-doc client map transfers wholesale (client indexes are
        doc-local, so client/removers planes carry over bit-exact),
        property planes remap by key, and the row's device planes are
        overwritten in one jitted update that also clears the sticky
        overflow flag. The source row must fit: count ≤ capacity and no
        overflow."""
        n = int(np.asarray(tmp.state.count[src_row]))
        assert n <= self.capacity and not tmp.overflowed()[src_row]
        planes = {k: np.asarray(getattr(tmp.state, k)[src_row][:n]).copy()
                  for k in _PLANES}
        planes["handle_op"] = self.remap_payload_handles(
            tmp, planes["handle_op"])
        self._client_idx[row] = dict(tmp._client_idx[src_row])
        self._cidx_cache = None  # the row's client-index map changed

        prop = np.zeros((self.capacity, self.n_props), np.int32)
        if tmp._has_props:
            self._has_props = True
            self.remap_props(tmp,
                             np.asarray(tmp.state.prop_val[src_row][:n]),
                             prop)

        def pad(a, fill=0):
            out = np.full((self.capacity,) + a.shape[1:], fill, np.int32)
            out[:n] = a
            return out

        self.state = _write_row_jit(
            self.state, jnp.int32(row),
            *(jnp.asarray(pad(planes[k],
                              NOT_REMOVED if k == "removed_seq" else 0))
              for k in _PLANES),
            jnp.asarray(prop), jnp.int32(n))
        # interval bookkeeping restarts from the rebuilt planes
        if self._intervals[row]:
            self._seed_tombs(row)

    def clear_doc(self, row: int) -> None:
        """Empty a row (used when a doc graduates off this store): planes
        zero, overflow flag cleared."""
        z = np.zeros((self.capacity,), np.int32)
        self.state = _write_row_jit(
            self.state, jnp.int32(row),
            *(jnp.asarray(np.full_like(z, NOT_REMOVED)
                          if k == "removed_seq" else z) for k in _PLANES),
            jnp.asarray(np.zeros((self.capacity, self.n_props), np.int32)),
            jnp.int32(0))

    def overflowed(self) -> np.ndarray:
        return np.asarray(self.state.overflow)

    def slot_usage(self) -> np.ndarray:
        return np.asarray(self.state.count)

    def digests(self) -> np.ndarray:
        return np.asarray(string_state_digest(self.state))

    # ----------------------------------------------------- snapshot / resume

    _SNAP_PLANES = StringOpInterner.SNAP_PLANES

    def snapshot(self) -> dict:
        """Device→host gather of the merged state plus the host interning
        tables (reference: channel ``summarize()``; SURVEY.md §7.7 — the
        Summarizer reuses the same kernels: resume = ``restore`` + tail
        replay through ``apply_messages``). Compact first for a minimal
        snapshot. Planes are trimmed to the widest doc's slot count."""
        st = self.state
        counts = np.asarray(st.count)
        n = max(int(counts.max()), 1)
        return {
            "planes": {k: np.asarray(getattr(st, k))[:, :n].copy()
                       for k in self._SNAP_PLANES},
            "count": counts.copy(),
            "overflow": np.asarray(st.overflow).copy(),
            "capacity": self.capacity,
            "n_props": self.n_props,
            "payloads": list(self._payloads),
            "client_idx": [dict(m) for m in self._client_idx],
            "prop_planes": dict(self._prop_planes),
            "prop_values": self._prop_values.export(),
            "has_props": self._has_props,
            "intervals": [{iid: [list(a) if a else None,
                                 list(b) if b else None, props]
                           for iid, (a, b, props) in per_doc.items()}
                          for per_doc in self._intervals],
            "interval_counter": self._interval_counter,
            "iv_min_seq": self._iv_min_seq.tolist(),
        }

    def snapshot_rows(self, rows, payloads_base: int,
                      prop_values_base: int) -> dict:
        """Incremental snapshot: ONLY the given doc rows' planes (one
        fused device→host gather) plus the append-only interner DELTAS
        since the last summary (``payloads_base`` / ``prop_values_base``
        are the table lengths recorded then). Clean rows are represented
        by reference to the previous summary — the handle-reuse half of
        SURVEY.md §2.16. Intervals ride in full (they mutate outside the
        op stream, so cheap full inclusion beats tracking)."""
        rows = np.ascontiguousarray(rows, np.int32)
        if len(rows):
            # pad the row list to a power of two (repeating row 0) so the
            # gather jit compiles one program per BUCKET, not one per
            # distinct dirty-row count
            n = len(rows)
            p2 = 1 << (n - 1).bit_length()
            rows_p = np.concatenate(
                [rows, np.full(p2 - n, rows[0], np.int32)])
            g = [np.asarray(x)[:n] for x in
                 _gather_rows_jit(self.state, jnp.asarray(rows_p))]
            w = max(int(g[8].max()), 1)
            planes = {k: g[i][:, :w].copy()
                      for i, k in enumerate(self._SNAP_PLANES)}
            counts, overflow = g[8].copy(), g[9].copy()
        else:
            planes = {k: np.zeros((0, 1), np.int32)
                      for k in self._SNAP_PLANES}
            counts = overflow = np.zeros((0,), np.int32)
        return {
            "rows": rows,
            "planes": planes,
            "count": counts,
            "overflow": overflow,
            "payloads_delta": list(self._payloads[payloads_base:]),
            "client_idx": {int(r): dict(self._client_idx[int(r)])
                           for r in rows},
            "prop_planes": dict(self._prop_planes),
            "prop_values_delta":
                self._prop_values.export_from(prop_values_base),
            "has_props": self._has_props,
            "intervals": [{iid: [list(a) if a else None,
                                 list(b) if b else None, props]
                           for iid, (a, b, props) in per_doc.items()}
                          for per_doc in self._intervals],
            "interval_counter": self._interval_counter,
            "iv_min_seq": self._iv_min_seq.tolist(),
        }

    def apply_row_snapshot(self, delta: dict) -> None:
        """Fold one ``snapshot_rows`` delta into this (restored-base)
        store: overwrite the dirty rows' device planes in one dispatch,
        extend the append-only interner tables, replace interval state."""
        self._payloads.extend(tuple(p) for p in delta["payloads_delta"])
        self._payload_chars += sum(
            len(p[1]) for p in delta["payloads_delta"])
        self._prop_planes = dict(delta["prop_planes"])
        self._prop_values.extend_from(delta["prop_values_delta"])
        self._has_props = self._has_props or delta["has_props"]
        # the plane map was replaced wholesale and dirty rows get new
        # client maps below — packed-props and client-lut caches are stale
        self._props_pack_cache = {}
        self._cidx_cache = None
        rows = np.asarray(delta["rows"], np.int32)
        if len(rows):
            for r, m in delta["client_idx"].items():
                self._client_idx[int(r)] = dict(m)
            w = delta["planes"]["seq"].shape[1]
            # power-of-two row bucket (repeat row 0 with its own values —
            # a duplicate scatter of identical values is a no-op): one
            # compiled scatter per bucket, not per dirty-row count
            n = len(rows)
            p2 = 1 << (n - 1).bit_length()
            rows_p = np.concatenate(
                [rows, np.full(p2 - n, rows[0], np.int32)])

            def bucket(a):
                return np.concatenate(
                    [a, np.repeat(a[:1], p2 - n, axis=0)]) if p2 > n else a

            def pad(a, fill=0):
                out = np.full((p2, self.capacity) + a.shape[2:],
                              fill, np.int32)
                out[:n, :w] = a
                out[n:] = out[:1]
                return jnp.asarray(out)

            prop = np.zeros((p2, self.capacity, self.n_props), np.int32)
            if "prop_val" in delta["planes"]:
                pv = delta["planes"]["prop_val"]
                prop[:n, :pv.shape[1]] = pv
                prop[n:] = prop[:1]
            self.state = _write_rows_jit(
                self.state, jnp.asarray(rows_p),
                *(pad(delta["planes"][k],
                      NOT_REMOVED if k == "removed_seq" else 0)
                  for k in _PLANES),
                jnp.asarray(prop), jnp.asarray(bucket(delta["count"])),
                jnp.asarray(bucket(delta["overflow"])))
        self._intervals = [
            {iid: (tuple(a) if a else None, tuple(b) if b else None,
                   dict(props))
             for iid, (a, b, props) in per_doc.items()}
            for per_doc in delta["intervals"]]
        self._interval_counter = delta["interval_counter"]
        self._iv_min_seq = np.asarray(delta["iv_min_seq"], np.int64)
        self._iv_docs = {d for d in range(self.n_docs)
                         if self._intervals[d]}
        for d in self._iv_docs:
            self._seed_tombs(d)

    @classmethod
    def restore(cls, snap: dict, mesh=None) -> "TensorStringStore":
        """Rebuild a store from ``snapshot()`` output: planes are padded
        back to capacity and re-uploaded; merging resumes mid-stream.
        Skips __init__'s device allocation (the snapshot fully replaces it)."""
        n_docs = snap["count"].shape[0]
        store = cls.__new__(cls)
        store.n_docs = n_docs
        store.capacity = snap["capacity"]
        store.n_props = snap["n_props"]
        store.mesh = mesh
        cap = snap["capacity"]
        full = {}
        for k in cls._SNAP_PLANES:
            small = np.asarray(snap["planes"][k])
            shape = (n_docs, cap) + small.shape[2:]
            fill = NOT_REMOVED if k == "removed_seq" else 0
            plane = np.full(shape, fill, np.int32)
            plane[:, :small.shape[1]] = small
            full[k] = jnp.asarray(plane)
        store.state = StringState(
            **full, count=jnp.asarray(snap["count"]),
            overflow=jnp.asarray(snap["overflow"]))
        if mesh is not None:
            from ..parallel.sharded import shard_store_state
            store.state = shard_store_state(store.state, mesh)
        store._payloads = [tuple(p) for p in snap["payloads"]]
        store._payload_chars = sum(len(p[1]) for p in store._payloads)
        store._client_idx = [dict(m) for m in snap["client_idx"]]
        store._prop_planes = dict(snap["prop_planes"])
        store._prop_values = ValueInterner.restore(snap["prop_values"])
        store._has_props = snap["has_props"]
        store._intervals = [
            {iid: (tuple(a) if a else None, tuple(b) if b else None,
                   dict(props))
             for iid, (a, b, props) in per_doc.items()}
            for per_doc in snap.get("intervals",
                                    [{} for _ in range(n_docs)])]
        store._interval_counter = snap.get("interval_counter", 0)
        store.last_profile = None
        store.last_rich_wire = None
        store._props_pack_cache = {}
        store._cidx_cache = None
        store._tab_pool = {}
        store.device_reads = 0
        store._iv_min_seq = np.asarray(
            snap.get("iv_min_seq", [0] * n_docs), np.int64)
        store._iv_tombs = [[] for _ in range(n_docs)]
        store._iv_docs = {d for d in range(n_docs)
                          if store._intervals[d]}
        for d in store._iv_docs:
            store._seed_tombs(d)
        return store
