"""Batched SharedMap op-apply kernel: the first end-to-end device slice.

Reference counterpart: the ``MapKernel.tryProcessMessage`` inner loop of
``@fluidframework/map`` (SURVEY.md §2.3) — but where the reference applies one
JSON op at a time per JS object, this kernel applies a (doc × op) batch of
packed records for thousands of documents in one jit'd call (SURVEY.md §7.3:
"the minimum slice").

Layout
------
State per document: ``K`` dense key slots (host interns string keys → slot
ids per doc). Three (D, K) int32 planes:

    present  — 1 if the key currently has a value
    value    — payload handle (host side table holds the actual JSON value)
    last_seq — seq of the write that set it (debug/digest/FWW-style queries)

Op batch: (D, O) planes (kind/a0/a1/seq) — the sequencer lays ops out densely
per doc, padding with NOOP. Total order within a doc = ascending op index.

Because map semantics are last-writer-wins with ``clear`` barriers, a whole
batch collapses without a sequential scan: for each (doc, key) the result
depends only on the LAST relevant op after the LAST clear — a pure reduction
over the op axis (max-index tricks), which vectorizes perfectly on the VPU.
No data-dependent control flow, fully static shapes.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .schema import OpKind, ValueInterner


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MapState:
    """Device-resident state for D documents × K key slots."""

    present: jax.Array   # (D, K) int32 0/1
    value: jax.Array     # (D, K) int32 payload handle
    last_seq: jax.Array  # (D, K) int32

    @staticmethod
    def create(n_docs: int, n_keys: int) -> "MapState":
        # three distinct buffers: the apply step donates its input state, and
        # XLA rejects donating one aliased buffer for multiple arguments
        z = lambda: jnp.zeros((n_docs, n_keys), dtype=jnp.int32)
        return MapState(present=z(), value=z(), last_seq=z())


def apply_map_batch(state: MapState, kind: jax.Array, a0: jax.Array,
                    a1: jax.Array, seq: jax.Array) -> MapState:
    """Apply a dense (D, O) batch of sequenced map ops.

    kind/a0/a1/seq: (D, O) int32 — OpKind, key slot, value handle, seq.
    Pure reduction over the op axis; jit/vmap/shard_map-friendly.
    """
    n_keys = state.present.shape[1]
    o_idx = jnp.arange(kind.shape[1], dtype=jnp.int32)          # (O,)

    is_clear = kind == OpKind.MAP_CLEAR
    is_set = kind == OpKind.MAP_SET
    is_del = kind == OpKind.MAP_DELETE

    # index of the last clear per doc (-1 if none)
    last_clear = jnp.max(jnp.where(is_clear, o_idx[None, :], -1), axis=1)  # (D,)

    # last relevant key-op per (doc, key): max op index among set/delete ops
    # targeting that key after the last clear
    key_onehot = a0[:, :, None] == jnp.arange(n_keys)[None, None, :]  # (D,O,K)
    relevant = (is_set | is_del) & (o_idx[None, :] > last_clear[:, None])
    cand = jnp.where(relevant[:, :, None] & key_onehot, o_idx[None, :, None], -1)
    last_op = jnp.max(cand, axis=1)                              # (D, K)

    had_clear = last_clear >= 0                                   # (D,)
    touched = last_op >= 0                                        # (D, K)

    safe_idx = jnp.maximum(last_op, 0)
    g = lambda plane: jnp.take_along_axis(plane, safe_idx, axis=1)
    op_is_set = g(kind) == OpKind.MAP_SET                         # (D, K)
    op_value = g(a1)
    op_seq = g(seq)

    base_present = jnp.where(had_clear[:, None], 0, state.present)
    base_value = jnp.where(had_clear[:, None], 0, state.value)
    base_seq = jnp.where(had_clear[:, None], 0, state.last_seq)

    present = jnp.where(touched, op_is_set.astype(jnp.int32), base_present)
    value = jnp.where(touched & op_is_set, op_value, base_value)
    last_seq = jnp.where(touched, jnp.where(op_is_set, op_seq, 0), base_seq)
    return MapState(present=present, value=value, last_seq=last_seq)


apply_map_batch_jit = jax.jit(apply_map_batch, donate_argnums=0)


@jax.jit
def _gather_map_rows_jit(state: "MapState", rows):
    """Fused device gather of selected doc rows (incremental summary)."""
    return (state.present[rows], state.value[rows], state.last_seq[rows])


@functools.partial(jax.jit, donate_argnums=0)
def _write_map_rows_jit(state: "MapState", rows, present, value, last_seq):
    """Overwrite selected doc rows (delta restore; duplicate padding rows
    scatter identical values — a no-op)."""
    return MapState(present=state.present.at[rows].set(present),
                    value=state.value.at[rows].set(value),
                    last_seq=state.last_seq.at[rows].set(last_seq))


@functools.partial(jax.jit,
                   static_argnames=("R", "O", "n_docs", "scatter_rows",
                                    "wide_vals"))
def map_columnar_unpack_jit(buf, R, O, n_docs, scatter_rows, wide_vals):
    """Unpack half of ``map_columnar_apply_jit`` (used standalone when
    the merge runs as a separate sharded program)."""
    return _map_unpack(buf, R, O, n_docs, scatter_rows, wide_vals)


@functools.partial(jax.jit, donate_argnums=0,
                   static_argnames=("R", "O", "n_docs", "scatter_rows",
                                    "wide_vals"))
def map_columnar_apply_jit(state, buf, R, O, n_docs, scatter_rows,
                           wide_vals):
    """Fused unpack + apply of ONE byte-packed columnar map batch: the
    host ships [kind u8 | key-slot u8 | value-handle u16/i32 | per-row
    seq bases i32 | row indices i32] as a single int32-word buffer
    (~4-7 B/op — each host→device transfer over a tunnel link pays the
    RTT, so the whole batch rides one copy; see the string store's
    ``_columnar_unpack_jit``). Per-op seqs rebuild on device from each
    row's base (nacked slots are NOOP and consumed no seq); map merge is
    the closed-form reduction of ``apply_map_batch``."""
    return apply_map_batch(
        state, *_map_unpack(buf, R, O, n_docs, scatter_rows, wide_vals))


def _map_unpack(buf, R, O, n_docs, scatter_rows, wide_vals):
    N = R * O

    def take_u8(off, n):
        w = -(-n // 4)
        words = jax.lax.slice_in_dim(buf, off, off + w, axis=0)
        v = jnp.stack([words & 0xFF, (words >> 8) & 0xFF,
                       (words >> 16) & 0xFF, (words >> 24) & 0xFF],
                      axis=1).reshape(4 * w)[:n]
        return v, off + w

    def take_u16(off, n):
        w = -(-n // 2)
        words = jax.lax.slice_in_dim(buf, off, off + w, axis=0)
        v = jnp.stack([words & 0xFFFF, (words >> 16) & 0xFFFF],
                      axis=1).reshape(2 * w)[:n]
        return v, off + w

    def take_i32(off, n):
        return jax.lax.slice_in_dim(buf, off, off + n, axis=0), off + n

    kind, off = take_u8(0, N)
    a0, off = take_u8(off, N)
    a1, off = (take_i32 if wide_vals else take_u16)(off, N)
    base, off = take_i32(off, R)
    rows, off = take_i32(off, R)

    kind = kind.reshape(R, O)
    a0 = a0.reshape(R, O)
    a1 = a1.reshape(R, O)
    valid = kind != int(OpKind.NOOP)
    seq = base[:, None] + jnp.cumsum(valid.astype(jnp.int32), axis=1)
    planes = (kind, a0, a1, seq)
    if scatter_rows:
        def full(p, fill):
            return jnp.full((n_docs, O), fill, jnp.int32).at[rows].set(p)

        planes = (full(kind, int(OpKind.NOOP)), full(a0, 0), full(a1, 0),
                  full(seq, 0))
    return planes


def map_state_digest(state: MapState) -> jax.Array:
    """Per-doc digest of converged state for cross-replica checks (the
    race-detection analog, SURVEY.md §5.2)."""
    k = jnp.arange(state.present.shape[1], dtype=jnp.int32)
    mix = state.present * (k[None, :] * 1103515245 + 12345) \
        + state.value * 40503 + state.last_seq
    return jnp.sum(jnp.where(state.present > 0, mix, 0), axis=1)


class TensorMapStore:
    """Host facade: many SharedMap documents resident on device.

    Interns string keys / JSON values into int32 handles, packs sequenced ops
    into dense (D, O) batches, applies them in one jit'd call, and reads back
    per-doc dicts. This is the serving-side merge engine; interactive
    optimistic editing stays in ``models.SharedMap`` (host).
    """

    def __init__(self, n_docs: int, n_keys: int = 64, mesh=None):
        self.n_docs = n_docs
        self.n_keys = n_keys
        # multi-chip: a 1-D "docs" mesh shards the planes by doc row; the
        # map merge is a per-doc closed-form reduction, so the sharded
        # apply is a collective-free shard_map of the same kernel
        self.mesh = mesh
        if mesh is not None and n_docs % mesh.devices.size != 0:
            raise ValueError(f"n_docs {n_docs} not divisible by mesh size "
                             f"{mesh.devices.size}")
        self.state = MapState.create(n_docs, n_keys)
        if mesh is not None:
            from ..parallel.sharded import shard_map_store_state
            self.state = shard_map_store_state(self.state, mesh)
        self._key_ids: List[Dict[str, int]] = [dict() for _ in range(n_docs)]
        self._interner = ValueInterner()

    # --------------------------------------------------------- capacity plane

    def capacity_stats(self) -> dict:
        """Capacity-plane report fragment (ISSUE 19)."""
        from ..utils import capacity as _cap
        host = _cap.list_nbytes(self.n_docs)
        for ids in self._key_ids:
            host += _cap.dict_nbytes(len(ids),
                                     _cap.INT_DICT_ENTRY_BYTES)
        host += _cap.interner_nbytes(len(self._interner),
                                     80 * len(self._interner))
        return {"host": {"interner": int(host)},
                "device": {"state": _cap.device_nbytes(self.state)}}

    # ------------------------------------------------------------- interning

    def key_slot(self, doc: int, key: str) -> int:
        ids = self._key_ids[doc]
        if key not in ids:
            if len(ids) >= self.n_keys:
                raise KeyError(f"doc {doc}: key capacity {self.n_keys} exhausted")
            ids[key] = len(ids)
        return ids[key]

    def value_handle(self, value) -> int:
        return self._interner.handle(value)

    # ----------------------------------------------------------------- apply

    def apply_batch(self, records) -> None:
        """records: iterable of (doc, kind, key, value, seq) with key=str,
        value=JSON for sets (None otherwise). Sequenced (seq ascending)."""
        per_doc: Dict[int, list] = {}
        for doc, kind, key, value, seq in records:
            slot = self.key_slot(doc, key) if key is not None else 0
            handle = self.value_handle(value) if kind == OpKind.MAP_SET else 0
            per_doc.setdefault(doc, []).append((int(kind), slot, handle, seq))
        if not per_doc:
            return
        # pad the op axis to a power-of-two bucket: a fresh (D, O) shape per
        # call would retrigger XLA compilation on nearly every batch
        widest = max(len(v) for v in per_doc.values())
        o = 8
        while o < widest:
            o *= 2
        kind = np.full((self.n_docs, o), int(OpKind.NOOP), dtype=np.int32)
        a0 = np.zeros((self.n_docs, o), dtype=np.int32)
        a1 = np.zeros((self.n_docs, o), dtype=np.int32)
        seq = np.zeros((self.n_docs, o), dtype=np.int32)
        for doc, ops in per_doc.items():
            for j, (k_, s_, h_, q_) in enumerate(ops):
                kind[doc, j] = k_
                a0[doc, j] = s_
                a1[doc, j] = h_
                seq[doc, j] = q_
        self.state = apply_map_batch_jit(
            self.state, jnp.asarray(kind), jnp.asarray(a0), jnp.asarray(a1),
            jnp.asarray(seq))

    # ----------------------------------------------------------------- reads

    def read_doc(self, doc: int) -> dict:
        present = np.asarray(self.state.present[doc])
        value = np.asarray(self.state.value[doc])
        out = {}
        for key, slot in self._key_ids[doc].items():
            if present[slot]:
                out[key] = self._interner.value(value[slot])
        return out

    def digests(self) -> np.ndarray:
        return np.asarray(map_state_digest(self.state))

    # ----------------------------------------------------- snapshot / resume

    def snapshot(self) -> dict:
        """Device→host gather + host interning tables (channel summarize();
        resume = ``restore`` + tail replay through ``apply_batch``)."""
        return {
            "present": np.asarray(self.state.present).copy(),
            "value": np.asarray(self.state.value).copy(),
            "last_seq": np.asarray(self.state.last_seq).copy(),
            "n_keys": self.n_keys,
            "key_ids": [dict(m) for m in self._key_ids],
            "values": self._interner.export(),
        }

    def snapshot_rows(self, rows, values_base: int) -> dict:
        """Incremental snapshot: only the given doc rows' planes (one
        fused device→host gather) plus the append-only value-interner
        DELTA since the base summary (``values_base`` = its table
        length). Clean rows ride by reference to the base (SURVEY.md
        §2.16 handle reuse)."""
        from .schema import pad_rows_pow2
        rows = np.ascontiguousarray(rows, np.int32)
        if len(rows):
            rows_p, _p2, n = pad_rows_pow2(rows)
            g = _gather_map_rows_jit(self.state, jnp.asarray(rows_p))
            present, value, last_seq = (np.asarray(x)[:n].copy()
                                        for x in g)
        else:
            present = value = last_seq = np.zeros((0, self.n_keys),
                                                  np.int32)
        return {
            "rows": rows,
            "present": present, "value": value, "last_seq": last_seq,
            "key_ids": {int(r): dict(self._key_ids[int(r)])
                        for r in rows},
            "values_delta": self._interner.export_from(values_base),
        }

    def apply_row_snapshot(self, delta: dict) -> None:
        """Fold one ``snapshot_rows`` delta into this (restored-base)
        store: overwrite the dirty rows' planes in one scatter, extend
        the append-only value table, replace the rows' key maps."""
        self._interner.extend_from(delta["values_delta"])
        rows = np.asarray(delta["rows"], np.int32)
        if not len(rows):
            return
        from .schema import bucket_rows, pad_rows_pow2
        for r, m in delta["key_ids"].items():
            self._key_ids[int(r)] = dict(m)
        rows_p, p2, n = pad_rows_pow2(rows)

        def bucket(a):
            return jnp.asarray(bucket_rows(a, p2, n))

        self.state = _write_map_rows_jit(
            self.state, jnp.asarray(rows_p), bucket(delta["present"]),
            bucket(delta["value"]), bucket(delta["last_seq"]))
        if self.mesh is not None:
            from ..parallel.sharded import shard_map_store_state
            self.state = shard_map_store_state(self.state, self.mesh)

    @classmethod
    def restore(cls, snap: dict, mesh=None) -> "TensorMapStore":
        store = cls.__new__(cls)
        store.n_docs = snap["present"].shape[0]
        store.n_keys = snap["n_keys"]
        store.mesh = mesh
        store.state = MapState(
            present=jnp.asarray(snap["present"]),
            value=jnp.asarray(snap["value"]),
            last_seq=jnp.asarray(snap["last_seq"]))
        if mesh is not None:
            from ..parallel.sharded import shard_map_store_state
            store.state = shard_map_store_state(store.state, mesh)
        store._key_ids = [dict(m) for m in snap["key_ids"]]
        store._interner = ValueInterner.restore(snap["values"])
        return store
