"""Host facade for the batched tree kernel: many SharedTree documents
resident on device.

Mirrors ``TensorStringStore``'s division of labor: the host interns
variable-size identities (node-id strings, field names, type names, JSON
values) into int32 handles and EXPANDS each oracle op dict into the guard +
record stream of ``tree_kernel`` (its module docstring documents the
grouping protocol); the device does all merge math. Reads reconstruct the
oracle's ``to_dict`` shape by walking the sibling linked lists host-side.

Reference counterpart: the serving half of ``@fluidframework/tree``
(SURVEY.md §2.6); oracle: ``models.shared_tree``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from .schema import ValueInterner
from .tree_kernel import (
    META_NESTED, ROOT_HANDLE, TreeOpKind, TreeState, _TREE_PLANES,
    apply_tree_batch_jit, tree_state_digest,
)

ROOT = "root"


class _Interner:
    """str ↔ dense int32 handle (1-based; 0 = none)."""

    def __init__(self, reserved=()):
        self._ids: Dict[str, int] = {}
        self._names: List[Optional[str]] = [None]
        for name in reserved:
            self.handle(name)

    def handle(self, name: str) -> int:
        if name not in self._ids:
            self._ids[name] = len(self._names)
            self._names.append(name)
        return self._ids[name]

    def name(self, handle: int) -> Optional[str]:
        return self._names[handle]

    def export(self) -> list:
        return list(self._names)

    @classmethod
    def restore(cls, names: list) -> "_Interner":
        it = cls()
        for n in names[1:]:
            it.handle(n)
        return it


class TensorTreeStore:
    def __init__(self, n_docs: int, capacity: int = 256):
        self.n_docs = n_docs
        self.capacity = capacity
        self.state = TreeState.create(n_docs, capacity)
        self._ids = _Interner(reserved=(ROOT,))      # handle 1 == ROOT
        assert self._ids.handle(ROOT) == ROOT_HANDLE
        self._fields = _Interner()
        self._types = _Interner()
        self._values = ValueInterner()

    # ----------------------------------------------------------- translation

    def _rec(self, kind, node=0, parent=0, after=0, field=0, value=0,
             type_=0, meta=0):
        return (int(kind), node, parent, after, field, value, type_, meta)

    def _vh(self, value) -> int:
        return 0 if value is None else self._values.handle(value)

    def _th(self, type_name) -> int:
        return 0 if type_name is None else self._types.handle(type_name)

    def _expand_insert(self, op: dict, out: list) -> None:
        """INS_BEGIN + one absent-guard per top-level spec + DFS records
        (nested records carry META_NESTED: 'parent created by this op')."""
        out.append(self._rec(TreeOpKind.INS_BEGIN))
        for spec in op["nodes"]:
            out.append(self._rec(TreeOpKind.INS_GUARD_ABSENT,
                                 node=self._ids.handle(spec["id"])))
        after = self._ids.handle(op["after"]) if op.get("after") else 0
        parent = self._ids.handle(op["parent"])
        field = self._fields.handle(op["field"])
        for spec in op["nodes"]:
            self._expand_spec(spec, parent, field, after, nested=False,
                              out=out)
            after = self._ids.handle(spec["id"])

    def _expand_spec(self, spec: dict, parent: int, field: int, after: int,
                     nested: bool, out: list) -> None:
        nid = self._ids.handle(spec["id"])
        out.append(self._rec(
            TreeOpKind.INSERT, node=nid, parent=parent, after=after,
            field=field, value=self._vh(spec.get("value")),
            type_=self._th(spec.get("type")),
            meta=META_NESTED if nested else 0))
        for fname, child_specs in (spec.get("children") or {}).items():
            fh = self._fields.handle(fname)
            prev = 0
            for child in child_specs:
                self._expand_spec(child, nid, fh, prev, nested=True,
                                  out=out)
                prev = self._ids.handle(child["id"])

    def _expand_edit(self, op: dict, out: list) -> None:
        kind = op["op"]
        if kind == "insert":
            self._expand_insert(op, out)
        elif kind == "remove":
            out.append(self._rec(TreeOpKind.INS_BEGIN))
            out.append(self._rec(TreeOpKind.REMOVE,
                                 node=self._ids.handle(op["id"])))
        elif kind == "move":
            out.append(self._rec(TreeOpKind.INS_BEGIN))
            out.append(self._rec(
                TreeOpKind.MOVE, node=self._ids.handle(op["id"]),
                parent=self._ids.handle(op["parent"]),
                after=self._ids.handle(op["after"]) if op.get("after")
                else 0,
                field=self._fields.handle(op["field"])))
        elif kind == "setValue":
            out.append(self._rec(TreeOpKind.INS_BEGIN))
            out.append(self._rec(TreeOpKind.SET_VALUE,
                                 node=self._ids.handle(op["id"]),
                                 value=self._vh(op["value"])))
        elif kind == "transaction":
            for sub in op["edits"]:
                self._expand_edit(sub, out)
        else:
            raise ValueError(f"unknown tree op {kind!r}")

    def _records_for(self, msg) -> list:
        """Expanded device records for one sequenced tree message."""
        op = msg.contents
        out: list = [self._rec(TreeOpKind.TXN_BEGIN)]
        if op["op"] == "transaction":
            for c in op.get("constraints", ()):
                if "nodeExists" in c:
                    out.append(self._rec(
                        TreeOpKind.TXN_GUARD_EXISTS,
                        node=self._ids.handle(c["nodeExists"])))
        self._expand_edit(op, out)
        return out

    # ----------------------------------------------------------------- apply

    def apply_messages(self, messages) -> None:
        per_doc: Dict[int, list] = {}
        per_doc_seq: Dict[int, list] = {}
        for doc, msg in messages:
            recs = self._records_for(msg)
            per_doc.setdefault(doc, []).extend(recs)
            per_doc_seq.setdefault(doc, []).extend([msg.seq] * len(recs))
        if not per_doc:
            return
        widest = max(len(v) for v in per_doc.values())
        o = 8
        while o < widest:
            o *= 2
        # vectorized packing: one np.array per doc's record list (C loop
        # over tuples) + one slice write per doc — not a per-element
        # Python double loop (VERDICT r3 missing #5)
        planes = np.zeros((9, self.n_docs, o), np.int32)
        for doc, recs in per_doc.items():
            arr = np.array(recs, np.int32)              # (n, 8)
            planes[0:8, doc, :len(recs)] = arr.T
            planes[8, doc, :len(recs)] = per_doc_seq[doc]
        # plane order for the kernel: kind,node,parent,after,field,value,
        # type_,seq,meta
        self.state = apply_tree_batch_jit(
            self.state, jnp.asarray(planes[0]), jnp.asarray(planes[1]),
            jnp.asarray(planes[2]), jnp.asarray(planes[3]),
            jnp.asarray(planes[4]), jnp.asarray(planes[5]),
            jnp.asarray(planes[6]), jnp.asarray(planes[8]),
            jnp.asarray(planes[7]))

    def apply_flat_inserts(self, rows, slot_of_row, parents, fields,
                           node_ids, afters, values, types, seqs) -> None:
        """Vectorized apply of N FLAT single-node inserts (op i creates
        ``node_ids[i]`` under ``parents[i]``/``fields[i]`` after
        ``afters[i]`` in doc row ``rows[i]``): the per-op record stream
        is a fixed 4-record pattern (TXN_BEGIN, INS_BEGIN, GUARD_ABSENT,
        INSERT), so the planes build as strided numpy writes — no per-op
        Python translation loop. ``slot_of_row[i]`` is op i's position
        among its doc's ops this batch (callers group by doc)."""
        n = len(node_ids)
        nid = np.fromiter((self._ids.handle(x) for x in node_ids),
                          np.int32, count=n)
        par = np.fromiter((self._ids.handle(x) for x in parents),
                          np.int32, count=n)
        aft = np.fromiter(
            (self._ids.handle(x) if x else 0 for x in afters),
            np.int32, count=n)
        fld = np.fromiter((self._fields.handle(x) for x in fields),
                          np.int32, count=n)
        val = np.fromiter((self._vh(v) for v in values), np.int32,
                          count=n)
        typ = np.fromiter((self._th(t) for t in types), np.int32,
                          count=n)
        width = int(np.max(slot_of_row)) + 1 if n else 1
        o = 8
        while o < 4 * width:
            o *= 2
        planes = np.zeros((9, self.n_docs, o), np.int32)
        base = np.asarray(slot_of_row, np.int64) * 4
        rws = np.asarray(rows, np.int64)
        # record pattern per op: kind plane gets [TXN_BEGIN, INS_BEGIN,
        # GUARD_ABSENT, INSERT]; id/attr planes light up per record role
        planes[0, rws, base + 0] = int(TreeOpKind.TXN_BEGIN)
        planes[0, rws, base + 1] = int(TreeOpKind.INS_BEGIN)
        planes[0, rws, base + 2] = int(TreeOpKind.INS_GUARD_ABSENT)
        planes[0, rws, base + 3] = int(TreeOpKind.INSERT)
        planes[1, rws, base + 2] = nid       # guard target
        planes[1, rws, base + 3] = nid       # inserted node
        planes[2, rws, base + 3] = par
        planes[3, rws, base + 3] = aft
        planes[4, rws, base + 3] = fld
        planes[5, rws, base + 3] = val
        planes[6, rws, base + 3] = typ
        sq = np.asarray(seqs, np.int64)
        for k in range(4):
            planes[8, rws, base + k] = sq
        self.state = apply_tree_batch_jit(
            self.state, jnp.asarray(planes[0]), jnp.asarray(planes[1]),
            jnp.asarray(planes[2]), jnp.asarray(planes[3]),
            jnp.asarray(planes[4]), jnp.asarray(planes[5]),
            jnp.asarray(planes[6]), jnp.asarray(planes[8]),
            jnp.asarray(planes[7]))

    # ----------------------------------------------------------------- reads

    def _pull(self, doc: int) -> dict:
        st = self.state
        return {k: np.asarray(getattr(st, k)[doc]) for k in _TREE_PLANES}

    def to_dict(self, doc: int) -> dict:
        """The oracle's ``to_dict`` shape, rebuilt from the planes."""
        p = self._pull(doc)
        live = p["node_id"] != 0
        by_id = {int(p["node_id"][i]): i for i in range(self.capacity)
                 if live[i]}

        def node_dict(nid: int) -> dict:
            i = by_id[nid]
            out = {"id": self._ids.name(nid),
                   "type": self._types.name(int(p["type_"][i]))
                   if p["type_"][i] else None,
                   "value": self._values.value(int(p["value"][i]))
                   if p["value"][i] else None}
            # group children by field, ordered by the linked list
            fields: Dict[int, list] = {}
            for j in range(self.capacity):
                if live[j] and int(p["parent"][j]) == nid:
                    fields.setdefault(int(p["field"][j]), []).append(j)
            children = {}
            for fh, slots in fields.items():
                ordered = self._chain_order(p, slots)
                children[self._fields.name(fh)] = [
                    node_dict(int(p["node_id"][j])) for j in ordered]
            if children:
                out["children"] = dict(sorted(children.items()))
            return out

        return node_dict(ROOT_HANDLE)

    def _chain_order(self, p, slots: list) -> list:
        """Order sibling slots by their prev/next chain (head: prev == 0)."""
        by_id = {int(p["node_id"][j]): j for j in slots}
        head = [j for j in slots if int(p["prev_sib"][j]) == 0]
        assert len(head) == 1, "broken sibling chain"
        order = [head[0]]
        while True:
            nxt = int(p["next_sib"][order[-1]])
            if nxt == 0:
                break
            order.append(by_id[nxt])
        assert len(order) == len(slots), "sibling chain mismatch"
        return order

    def node_value(self, doc: int, node_id: str):
        p = self._pull(doc)
        nh = self._ids.handle(node_id)
        sel = p["node_id"] == nh
        if not sel.any():
            raise KeyError(node_id)
        return self._values.value(int(p["value"][sel][0])) \
            if p["value"][sel][0] else None

    def has_node(self, doc: int, node_id: str) -> bool:
        if node_id not in self._ids._ids:
            return False
        return bool((self._pull(doc)["node_id"] ==
                     self._ids.handle(node_id)).any())

    def node_count(self, doc: int) -> int:
        return int((np.asarray(self.state.node_id[doc]) != 0).sum())

    def overflowed(self) -> np.ndarray:
        return np.asarray(self.state.overflow)

    # -------------------------------------------------- overflow recovery ops
    # (the serving engine's escape hatch — mirrors TensorStringStore's
    # clear_doc/adopt_doc so tree recovery stays the same shape)

    def share_interners(self, other: "TensorTreeStore") -> None:
        """Alias ``other``'s interner tables (append-only) so handles in
        this store mean the same strings/values as in ``other`` — the
        precondition for ``other.adopt_doc`` copying our planes verbatim."""
        self._ids = other._ids
        self._fields = other._fields
        self._types = other._types
        self._values = other._values

    def clear_doc(self, row: int) -> None:
        """Reset one row to the empty tree (root only, overflow cleared)."""
        st = self.state
        fresh = TreeState.create(1, self.capacity)
        self.state = dataclasses.replace(
            st,
            **{k: getattr(st, k).at[row].set(getattr(fresh, k)[0])
               for k in _TREE_PLANES},
            overflow=st.overflow.at[row].set(0))

    def high_water(self, doc: int = 0) -> int:
        """1 + highest live slot index (root counts), for fit checks."""
        live = np.asarray(self.state.node_id[doc]) != 0
        return int(np.max(np.nonzero(live)[0])) + 1 if live.any() else 0

    def repack(self, doc: int = 0) -> None:
        """Compact a doc's live slots to the lowest indices. Slot position
        carries NO meaning in this representation (order/attachment are id
        handles — tree_kernel module docstring), so this is a pure
        permutation; it exists so a rebuilt doc whose history churned
        through many slots fits back into a small tier."""
        st = self.state
        p = {k: np.asarray(getattr(st, k)[doc]) for k in _TREE_PLANES}
        live = np.nonzero(p["node_id"] != 0)[0]
        updates = {}
        for k in _TREE_PLANES:
            row = np.zeros((self.capacity,), np.int32)
            row[:len(live)] = p[k][live]
            updates[k] = getattr(st, k).at[doc].set(jnp.asarray(row))
        self.state = dataclasses.replace(st, **updates)

    def adopt_doc(self, row: int, tmp: "TensorTreeStore") -> None:
        """Upload single-doc store ``tmp`` (which MUST share this store's
        interners — see ``share_interners``) into ``row``. Caller checks
        ``tmp.high_water() <= self.capacity`` first."""
        hw = tmp.high_water()
        assert hw <= self.capacity, "doc does not fit this tier"
        st = self.state
        updates = {}
        for k in _TREE_PLANES:
            src = np.zeros((self.capacity,), np.int32)
            src[:hw] = np.asarray(getattr(tmp.state, k)[0, :hw])
            updates[k] = getattr(st, k).at[row].set(jnp.asarray(src))
        self.state = dataclasses.replace(
            st, **updates, overflow=st.overflow.at[row].set(0))

    def digests(self) -> np.ndarray:
        return np.asarray(tree_state_digest(self.state))

    # ----------------------------------------------------- snapshot / resume

    def snapshot(self) -> dict:
        st = self.state
        return {
            "planes": {k: np.asarray(getattr(st, k)).copy()
                       for k in _TREE_PLANES},
            "overflow": np.asarray(st.overflow).copy(),
            "capacity": self.capacity,
            "ids": self._ids.export(),
            "fields": self._fields.export(),
            "types": self._types.export(),
            "values": self._values.export(),
        }

    @classmethod
    def restore(cls, snap: dict) -> "TensorTreeStore":
        n_docs = snap["overflow"].shape[0]
        store = cls.__new__(cls)
        store.n_docs = n_docs
        store.capacity = snap["capacity"]
        store.state = TreeState(
            **{k: jnp.asarray(snap["planes"][k]) for k in _TREE_PLANES},
            overflow=jnp.asarray(snap["overflow"]))
        store._ids = _Interner.restore(snap["ids"])
        store._fields = _Interner.restore(snap["fields"])
        store._types = _Interner.restore(snap["types"])
        store._values = ValueInterner.restore(snap["values"])
        return store
