"""Host facade for the batched tree kernel: many SharedTree documents
resident on device.

Mirrors ``TensorStringStore``'s division of labor: the host interns
variable-size identities (node-id strings, field names, type names, JSON
values) into int32 handles and EXPANDS each oracle op dict into the guard +
record stream of ``tree_kernel`` (its module docstring documents the
grouping protocol); the device does all merge math. Reads reconstruct the
oracle's ``to_dict`` shape by walking the sibling linked lists host-side.

Reference counterpart: the serving half of ``@fluidframework/tree``
(SURVEY.md §2.6); oracle: ``models.shared_tree``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from .schema import ValueInterner
from .tree_kernel import (
    META_NESTED, ROOT_HANDLE, TreeOpKind, TreeState, _TREE_PLANES,
    apply_tree_planes_jit, apply_tree_wire_jit, gather_tree_rows_jit,
    tree_state_digest, write_tree_rows_jit,
)

ROOT = "root"


#: Floor of the numeric-id namespace: handles ≥ ANON_BASE are ANONYMOUS —
#: their name is synthesized as ``#<handle>`` and never interned. This is
#: the id-compressor role (SURVEY.md §2.11: distributed UUID→small-int
#: compression): clients ``reserve()`` numeric clusters and ship ids as
#: ints, so the serving hot path never touches a string table.
ANON_BASE = 1 << 20


class _Interner:
    """str ↔ dense int32 handle (1-based; 0 = none). Handles below
    ``ANON_BASE`` are interned strings; handles at or above it are the
    numeric-id namespace (name ``#<handle>``, no storage)."""

    def __init__(self, reserved=()):
        self._ids: Dict[str, int] = {}
        self._names: List[Optional[str]] = [None]
        self._next_anon = ANON_BASE
        for name in reserved:
            self.handle(name)

    @staticmethod
    def _anon_handle(name: str) -> Optional[int]:
        if name.startswith("#"):
            tail = name[1:]
            if tail.isdigit():
                h = int(tail)
                if h >= ANON_BASE:
                    return h
        return None

    def handle(self, name: str) -> int:
        h = self._anon_handle(name)
        if h is not None:
            return h
        if name not in self._ids:
            h = len(self._names)
            if h >= ANON_BASE:
                raise OverflowError("string-id space exhausted; use "
                                    "numeric ids (reserve/#-names)")
            self._ids[name] = h
            self._names.append(name)
        return self._ids[name]

    def peek(self, name: str) -> Optional[int]:
        """Handle if known (or anonymous), WITHOUT interning."""
        h = self._anon_handle(name)
        return h if h is not None else self._ids.get(name)

    def reserve(self, count: int) -> int:
        """Allocate a cluster of ``count`` anonymous numeric ids;
        returns the base handle (ids = base..base+count-1, names
        ``#<h>``)."""
        base = self._next_anon
        self._next_anon = base + count
        return base

    def bulk(self, items) -> list:
        """Handles for a whole table at once (the columnar-ingest hot
        path: local-var loop, one dict probe per item). Table entries
        may be ints (pre-compressed numeric handles, passed through)."""
        ids = self._ids
        names = self._names
        get = ids.get
        anon = self._anon_handle
        out = []
        append = out.append
        for s in items:
            if type(s) is int:
                append(s)
                continue
            v = get(s)
            if v is None:
                v = anon(s)
                if v is None:
                    v = len(names)
                    if v >= ANON_BASE:
                        raise OverflowError("string-id space exhausted")
                    ids[s] = v
                    names.append(s)
            append(v)
        return out

    def name(self, handle: int) -> Optional[str]:
        return f"#{handle}" if handle >= ANON_BASE \
            else self._names[handle]

    def __len__(self) -> int:
        return len(self._names)

    def export_from(self, base_len: int) -> list:
        """Names appended since ``base_len`` (incremental-summary delta;
        the table is append-only)."""
        return list(self._names[base_len:])

    def extend_from(self, names: list) -> None:
        for n in names:
            self.handle(n)

    def export(self) -> dict:
        return {"names": list(self._names), "next_anon": self._next_anon}

    @classmethod
    def restore(cls, snap) -> "_Interner":
        it = cls()
        names = snap["names"] if isinstance(snap, dict) else snap
        for n in names[1:]:
            it.handle(n)
        if isinstance(snap, dict):
            it._next_anon = snap["next_anon"]
        return it


class RecordEmitter:
    """Canonical op-dict → kernel-record encoding, shared by the store's
    message path (global interners) and the client wire encoder (local
    per-batch tables); ``server.tree_wire.decode_op`` inverts it.

    The encoding is throughput-shaped: a standalone flat edit compresses
    to ONE solo record; the begin/guard group protocol appears only where
    atomicity actually needs it (multi-node inserts, transactions)."""

    def __init__(self, h_id, h_field, h_value, h_type):
        self._id = h_id
        self._field = h_field
        self._value = h_value
        self._type = h_type

    @staticmethod
    def _rec(kind, node=0, parent=0, after=0, field=0, value=0,
             type_=0, meta=0):
        return (int(kind), node, parent, after, field, value, type_, meta)

    def _vh(self, value) -> int:
        return 0 if value is None else self._value(value)

    def _th(self, type_name) -> int:
        return 0 if type_name is None else self._type(type_name)

    def _emit_specs(self, op: dict, out: list, solo: bool) -> None:
        """DFS INSERT records for every spec of an insert op (top-level
        chained by ``after``; nested records carry META_NESTED)."""
        after = self._id(op["after"]) if op.get("after") else 0
        parent = self._id(op["parent"])
        field = self._field(op["field"])
        kind = TreeOpKind.INSERT_SOLO if solo else TreeOpKind.INSERT
        for spec in op["nodes"]:
            self._emit_spec(spec, parent, field, after, kind, nested=False,
                            out=out)
            after = self._id(spec["id"])

    def _emit_spec(self, spec: dict, parent: int, field: int, after: int,
                   kind, nested: bool, out: list) -> None:
        nid = self._id(spec["id"])
        out.append(self._rec(
            kind, node=nid, parent=parent, after=after,
            field=field, value=self._vh(spec.get("value")),
            type_=self._th(spec.get("type")),
            meta=META_NESTED if nested else 0))
        for fname, child_specs in (spec.get("children") or {}).items():
            fh = self._field(fname)
            prev = 0
            for child in child_specs:
                self._emit_spec(child, nid, fh, prev, kind, nested=True,
                                out=out)
                prev = self._id(child["id"])

    def emit_op(self, op: dict) -> list:
        """Record tuples for ONE standalone sequenced op."""
        kind = op["op"]
        out: list = []
        if kind == "insert":
            if len(op["nodes"]) == 1:
                # single top-level spec: the INSERT record's own absent
                # check IS the oracle's guard; nested specs gate on
                # created_seq — no flags involved, so everything is solo
                self._emit_specs(op, out, solo=True)
            else:
                # multi-node all-or-nothing needs the guard group; the
                # TXN_BEGIN resets BOTH flags left over from prior ops
                out.append(self._rec(TreeOpKind.TXN_BEGIN))
                for spec in op["nodes"]:
                    out.append(self._rec(TreeOpKind.INS_GUARD_ABSENT,
                                         node=self._id(spec["id"])))
                self._emit_specs(op, out, solo=False)
        elif kind == "remove":
            out.append(self._rec(TreeOpKind.REMOVE_SOLO,
                                 node=self._id(op["id"])))
        elif kind == "move":
            out.append(self._rec(
                TreeOpKind.MOVE_SOLO, node=self._id(op["id"]),
                parent=self._id(op["parent"]),
                after=self._id(op["after"]) if op.get("after") else 0,
                field=self._field(op["field"])))
        elif kind == "setValue":
            out.append(self._rec(TreeOpKind.SET_SOLO,
                                 node=self._id(op["id"]),
                                 value=self._vh(op["value"])))
        elif kind == "transaction":
            cons = [c["nodeExists"] for c in op.get("constraints", ())
                    if "nodeExists" in c]
            if cons:
                # the first constraint rides the begin record (fused
                # reset+guard — one record less per transaction)
                out.append(self._rec(TreeOpKind.TXN_BEGIN_EXISTS,
                                     node=self._id(cons[0])))
                for cn in cons[1:]:
                    out.append(self._rec(TreeOpKind.TXN_GUARD_EXISTS,
                                         node=self._id(cn)))
            else:
                out.append(self._rec(TreeOpKind.TXN_BEGIN))
            # each edit is flag-gated (ok_txn holds the constraint gate);
            # ok_ins is re-reset (INS_BEGIN) only when a previous edit's
            # guards may have dirtied it — edits are independent
            dirty = False
            for sub in op["edits"]:
                dirty = self._emit_txn_edit(sub, out, dirty)
        else:
            raise ValueError(f"unknown tree op {kind!r}")
        return out

    def _emit_txn_edit(self, op: dict, out: list, dirty: bool) -> bool:
        kind = op["op"]
        if kind == "insert":
            guarded = len(op["nodes"]) > 1
            if dirty:
                out.append(self._rec(TreeOpKind.INS_BEGIN))
            if guarded:
                for spec in op["nodes"]:
                    out.append(self._rec(TreeOpKind.INS_GUARD_ABSENT,
                                         node=self._id(spec["id"])))
            self._emit_specs(op, out, solo=False)
            return guarded
        if dirty:
            out.append(self._rec(TreeOpKind.INS_BEGIN))
        if kind == "remove":
            out.append(self._rec(TreeOpKind.REMOVE,
                                 node=self._id(op["id"])))
        elif kind == "move":
            out.append(self._rec(
                TreeOpKind.MOVE, node=self._id(op["id"]),
                parent=self._id(op["parent"]),
                after=self._id(op["after"]) if op.get("after") else 0,
                field=self._field(op["field"])))
        elif kind == "setValue":
            out.append(self._rec(TreeOpKind.SET_VALUE,
                                 node=self._id(op["id"]),
                                 value=self._vh(op["value"])))
        else:
            # nested transactions cannot share the single ok_txn gate;
            # the serving engine rejects them at ingress (_valid_edit)
            # and the client API cannot produce them ("transactions do
            # not nest" — models/shared_tree.py)
            raise ValueError(f"unsupported edit inside transaction: "
                             f"{kind!r}")
        return False


def _pow2_at_least(n: int, floor: int = 1) -> int:
    o = floor
    while o < n:
        o *= 2
    return o


def pack_wire_records(recs_k: np.ndarray, rec_op_k: np.ndarray,
                      rows_r: np.ndarray, r_floor: int = 256,
                      bufs=None, id_t=np.uint16, val_t=np.uint16):
    """Width-coded wire buffers for kept records — THE upload layout of
    ``tree_kernel.apply_tree_wire`` (cols: kind|meta<<4 + first-of-op
    bit, field, type; u16/u32 local ids/values; u16 row + u8/u16 pos
    with the ``pos == o`` drop sentinel; records pow2-padded to
    ``r_floor`` buckets). One implementation shared by the serving
    dispatch and the bench's kernel-only phase. Returns (cols, ids,
    vals, row, pos, o), or None when the widest doc exceeds the u16
    pos budget.

    ``id_t``/``val_t``: dtype of the id/value index lanes — u16 by
    default, widened to u32 by the caller when a batch's id or value
    table outgrows 65534 entries (big general waves; still a fraction
    of the dense planes' bytes).

    ``bufs``: optional ``(rb, pos_dtype, id_dtype, val_dtype) ->
    (cols, ids, vals, row, pos)`` allocator (the store's pow2 wire
    pool). Pooled buffers are NOT zeroed: only the pos padding is
    filled (``pos == o`` drops the record — both kernel scatters key
    on (row, pos) with mode="drop", so stale garbage in the other
    planes' tails is never applied)."""
    r = len(recs_k)
    pos, widest = positions_in_doc(rows_r)
    o = _pow2_at_least(max(widest, 1))
    if o > 0xFFFF:
        return None
    rb = _pow2_at_least(max(r, 1), floor=r_floor)
    pos_t = np.uint8 if o <= 128 else np.uint16
    if bufs is not None:
        cols, idsb, valsb, rowb, posb = bufs(rb, pos_t, id_t, val_t)
        posb[r:] = o   # the drop sentinel is the only padding that matters
    else:
        cols = np.zeros((rb, 3), np.uint8)
        idsb = np.zeros((rb, 3), id_t)
        valsb = np.zeros(rb, val_t)
        rowb = np.zeros(rb, np.uint16)
        posb = np.full(rb, o, pos_t)   # padding records drop
    if r:
        first = np.empty(r, np.uint8)
        first[0] = 1
        first[1:] = rec_op_k[1:] != rec_op_k[:-1]
        cols[:r, 0] = recs_k[:, 0] | \
            ((recs_k[:, 7] | (first << 1)) << 4)
        cols[:r, 1] = recs_k[:, 4]
        cols[:r, 2] = recs_k[:, 6]
        idsb[:r] = recs_k[:, 1:4]
        valsb[:r] = recs_k[:, 5]
        rowb[:r] = rows_r
        posb[:r] = pos
    return cols, idsb, valsb, rowb, posb, o


def positions_in_doc(rows: np.ndarray):
    """Per-record position among its doc's records (flat order preserved
    per doc); returns (pos, widest_doc_count)."""
    order = np.argsort(rows, kind="stable")
    r_sorted = rows[order]
    starts = np.r_[0, np.flatnonzero(np.diff(r_sorted)) + 1]
    sizes = np.diff(np.r_[starts, len(r_sorted)])
    pos_sorted = np.arange(len(r_sorted)) - np.repeat(starts, sizes)
    pos = np.empty_like(pos_sorted)
    pos[order] = pos_sorted
    return pos, (int(sizes.max()) if len(sizes) else 0)


#: wire/map pool depth cap — bounds retained host memory at pipeline
#: depths beyond the steady state (the string store's _tab_pool cap)
_WIRE_POOL_DEPTH = 4


class PrepackedWire:
    """One tree record wave's wire buffers + interner table maps, packed
    AHEAD of sequencing on the pipeline's pack worker. Every record is
    packed (nacks resolve at dispatch, which discards the prepack on
    the rare nacked wave and repacks inline). Buffers come from the
    store's pow2 pools and return via ``release_wire`` — safe right
    after the dispatch call, because ``jnp.asarray`` copies host
    buffers at the jit boundary (the pool never aliases a live
    upload)."""

    __slots__ = ("cols", "idsb", "valsb", "rowb", "posb", "o",
                 "id_map", "f_map", "t_map", "v_map")


class TensorTreeStore:
    def __init__(self, n_docs: int, capacity: int = 256, mesh=None):
        """``mesh``: a 1-D ``docs`` device mesh shards the planes by doc
        row; the packed-plane apply runs as a collective-free shard_map
        of the same record scan (tree merge is per-doc math)."""
        self.n_docs = n_docs
        self.capacity = capacity
        self.mesh = mesh
        self.state = TreeState.create(n_docs, capacity)
        if mesh is not None:
            from ..parallel.sharded import shard_tree_store_state
            self.state = shard_tree_store_state(self.state, mesh)
        self._ids = _Interner(reserved=(ROOT,))      # handle 1 == ROOT
        assert self._ids.handle(ROOT) == ROOT_HANDLE
        self._fields = _Interner()
        self._types = _Interner()
        self._values = ValueInterner()
        # pow2 wire/map buffer pools for the prepacked wire path, keyed
        # by bucket size (GIL-atomic list push/pop: the pack worker pops
        # while the dispatch stage releases — the string store's
        # _tab_pool discipline)
        self._wire_pool: Dict[tuple, list] = {}
        self._map_pool: Dict[int, list] = {}

    # --------------------------------------------------------- capacity plane

    def capacity_stats(self) -> dict:
        """Capacity-plane report fragment (ISSUE 19): interner tables
        host-side, tree planes device-side."""
        from ..utils import capacity as _cap
        host = 0
        for it in (self._ids, self._fields, self._types):
            # names list + ids dict; ~24 chars/name payload average
            host += _cap.interner_nbytes(len(it._names),
                                         73 * len(it._names))
        host += _cap.interner_nbytes(len(self._values),
                                     80 * len(self._values))
        return {"host": {"interner": int(host)},
                "device": {"state": _cap.device_nbytes(self.state)}}

    # ----------------------------------------------------------- translation

    @property
    def emitter(self) -> RecordEmitter:
        return RecordEmitter(self._ids.handle, self._fields.handle,
                             self._values.handle, self._types.handle)

    def _records_for(self, msg) -> list:
        """Expanded device records for one sequenced tree message."""
        return self.emitter.emit_op(msg.contents)

    # ----------------------------------------------------------------- apply

    def _apply_planes(self, planes: np.ndarray) -> None:
        """Dispatch a packed (9, D, O) record-plane batch (plane order:
        kind, node, parent, after, field, value, type_, meta, seq) as ONE
        contiguous host→device transfer. On a mesh the SAME scan runs as
        a collective-free shard_map over each chip's doc block."""
        if self.mesh is not None:
            from ..parallel.sharded import sharded_tree_apply
            self.state = sharded_tree_apply(self.mesh)(
                self.state, jnp.asarray(planes))
            return
        self.state = apply_tree_planes_jit(self.state, jnp.asarray(planes))

    def pack_records(self, rows: np.ndarray, recs: np.ndarray,
                     seqs: np.ndarray) -> np.ndarray:
        """Scatter flat records into dense (9, D, O) planes. ``rows`` is
        each record's doc row; per-doc record ORDER is flat order (the
        sequencer's total order); O is the pow2 bucket of the widest doc
        (bounds recompiles)."""
        pos, widest = positions_in_doc(rows)
        o = _pow2_at_least(max(widest, 1))
        planes = np.zeros((9, self.n_docs, o), np.int32)
        for p in range(8):
            planes[p, rows, pos] = recs[:, p]
        planes[8, rows, pos] = seqs
        return planes

    def apply_wire(self, cols, ids, vals, row, pos, base, id_map, f_map,
                   t_map, v_map, o: int) -> None:
        """Dispatch one compact-wire batch (see tree_kernel
        ``apply_tree_wire`` for the buffer contract)."""
        self.state = apply_tree_wire_jit(
            self.state, jnp.asarray(cols), jnp.asarray(ids),
            jnp.asarray(vals), jnp.asarray(row), jnp.asarray(pos),
            jnp.asarray(base), jnp.asarray(id_map), jnp.asarray(f_map),
            jnp.asarray(t_map), jnp.asarray(v_map), o=o)

    # ------------------------------------------------------- prepacked wire

    def _wire_buffers(self, rb: int, pos_t, id_t, val_t):
        """Pop (or allocate) one pow2 wire-buffer set; tails are NOT
        zeroed — ``pack_wire_records`` fills the pos drop sentinel."""
        key = (rb, np.dtype(pos_t).itemsize, np.dtype(id_t).itemsize,
               np.dtype(val_t).itemsize)
        stack = self._wire_pool.get(key)
        if stack:
            return stack.pop()
        return (np.empty((rb, 3), np.uint8), np.empty((rb, 3), id_t),
                np.empty(rb, val_t), np.empty(rb, np.uint16),
                np.empty(rb, pos_t))

    def _pad_map(self, items, interner) -> np.ndarray:
        """Pooled pow2 local-index → interner-handle map. Only
        ``[0, len(items)]`` is ever gathered by a validated record
        (handle 0 == none), so the stale tail needs no zeroing."""
        cap = _pow2_at_least(len(items) + 1, floor=8)
        stack = self._map_pool.get(cap)
        m = stack.pop() if stack else np.empty(cap, np.int32)
        m[0] = 0
        if items:
            m[1:len(items) + 1] = interner.bulk(items)
        return m

    def prepack_wire(self, recs: np.ndarray, rec_op: np.ndarray,
                     rows_r: np.ndarray, tables: dict,
                     r_floor: int = 256) -> Optional[PrepackedWire]:
        """Pack ALL of a wave's records + interner maps into pooled
        pow2 wire buffers ahead of sequencing (the pipeline's pack
        worker; the ``ops/string_store.prepack_planes`` analog).
        Returns None when the widest doc overflows the u16 pos budget
        (the dense path must take the wave). The id/value index lanes
        widen to u32 when a table outgrows the u16 budget — big general
        waves (one fresh node id per op) stay on the wire instead of
        falling to dense planes."""
        packed = pack_wire_records(
            recs, rec_op, rows_r, r_floor=r_floor, bufs=self._wire_buffers,
            id_t=np.uint16 if len(tables["ids"]) < 0xFFFF else np.uint32,
            val_t=(np.uint16 if len(tables["values"]) < 0xFFFF
                   else np.uint32))
        if packed is None:
            return None
        pp = PrepackedWire()
        pp.cols, pp.idsb, pp.valsb, pp.rowb, pp.posb, pp.o = packed
        pp.id_map = self._pad_map(tables["ids"], self._ids)
        pp.f_map = self._pad_map(tables["fields"], self._fields)
        pp.t_map = self._pad_map(tables["types"], self._types)
        pp.v_map = self._pad_map(tables["values"], self._values)
        return pp

    def apply_wire_prepacked(self, pp: PrepackedWire,
                             base: np.ndarray) -> None:
        """Dispatch a prepacked wave (``base`` arrives post-sequencing)
        and return its buffers to the pools — the jit boundary copied
        them."""
        self.apply_wire(pp.cols, pp.idsb, pp.valsb, pp.rowb, pp.posb,
                        base, pp.id_map, pp.f_map, pp.t_map, pp.v_map,
                        pp.o)
        self.release_wire(pp)

    def release_wire(self, pp: PrepackedWire) -> None:
        """Return a prepack's pooled buffers (after dispatch, or when a
        nacked wave discards its prepack for the inline repack)."""
        key = (len(pp.posb), pp.posb.dtype.itemsize,
               pp.idsb.dtype.itemsize, pp.valsb.dtype.itemsize)
        stack = self._wire_pool.setdefault(key, [])
        if len(stack) < _WIRE_POOL_DEPTH:
            stack.append((pp.cols, pp.idsb, pp.valsb, pp.rowb, pp.posb))
        for m in (pp.id_map, pp.f_map, pp.t_map, pp.v_map):
            s = self._map_pool.setdefault(len(m), [])
            if len(s) < _WIRE_POOL_DEPTH:
                s.append(m)

    def apply_records(self, rows: np.ndarray, recs: np.ndarray,
                      seqs: np.ndarray) -> None:
        """Apply flat (R, 8) record tuples with per-record doc rows and
        seqs — the raw path shared by columnar ingest, recovery replay,
        and the message path below."""
        if len(recs) == 0:
            return
        self._apply_planes(self.pack_records(
            np.asarray(rows, np.int64), np.asarray(recs, np.int32),
            np.asarray(seqs, np.int64)))

    def apply_messages(self, messages) -> None:
        rows: list = []
        recs_all: list = []
        seqs: list = []
        for doc, msg in messages:
            recs = self._records_for(msg)
            recs_all.extend(recs)
            rows.extend([doc] * len(recs))
            seqs.extend([msg.seq] * len(recs))
        if not recs_all:
            return
        self.apply_records(np.asarray(rows, np.int64),
                           np.array(recs_all, np.int32),
                           np.asarray(seqs, np.int64))


    # ----------------------------------------------------------------- reads

    def _pull(self, doc: int) -> dict:
        st = self.state
        return {k: np.asarray(getattr(st, k)[doc]) for k in _TREE_PLANES}

    def to_dict(self, doc: int) -> dict:
        """The oracle's ``to_dict`` shape, rebuilt from the planes."""
        p = self._pull(doc)
        live = p["node_id"] != 0
        by_id = {int(p["node_id"][i]): i for i in range(self.capacity)
                 if live[i]}

        def node_dict(nid: int) -> dict:
            i = by_id[nid]
            out = {"id": self._ids.name(nid),
                   "type": self._types.name(int(p["type_"][i]))
                   if p["type_"][i] else None,
                   "value": self._values.value(int(p["value"][i]))
                   if p["value"][i] else None}
            # group children by field, ordered by the linked list
            fields: Dict[int, list] = {}
            for j in range(self.capacity):
                if live[j] and int(p["parent"][j]) == nid:
                    fields.setdefault(int(p["field"][j]), []).append(j)
            children = {}
            for fh, slots in fields.items():
                ordered = self._chain_order(p, slots)
                children[self._fields.name(fh)] = [
                    node_dict(int(p["node_id"][j])) for j in ordered]
            if children:
                out["children"] = dict(sorted(children.items()))
            return out

        return node_dict(ROOT_HANDLE)

    def _chain_order(self, p, slots: list) -> list:
        """Order sibling slots by their prev/next chain (head: prev == 0)."""
        by_id = {int(p["node_id"][j]): j for j in slots}
        head = [j for j in slots if int(p["prev_sib"][j]) == 0]
        assert len(head) == 1, "broken sibling chain"
        order = [head[0]]
        while True:
            nxt = int(p["next_sib"][order[-1]])
            if nxt == 0:
                break
            order.append(by_id[nxt])
        assert len(order) == len(slots), "sibling chain mismatch"
        return order

    def node_value(self, doc: int, node_id: str):
        p = self._pull(doc)
        nh = self._ids.peek(node_id)
        if nh is None:
            raise KeyError(node_id)
        sel = p["node_id"] == nh
        if not sel.any():
            raise KeyError(node_id)
        return self._values.value(int(p["value"][sel][0])) \
            if p["value"][sel][0] else None

    def has_node(self, doc: int, node_id: str) -> bool:
        nh = self._ids.peek(node_id)
        if nh is None:
            return False
        return bool((self._pull(doc)["node_id"] == nh).any())

    def node_count(self, doc: int) -> int:
        return int((np.asarray(self.state.node_id[doc]) != 0).sum())

    def overflowed(self) -> np.ndarray:
        return np.asarray(self.state.overflow)

    # -------------------------------------------------- overflow recovery ops
    # (the serving engine's escape hatch — mirrors TensorStringStore's
    # clear_doc/adopt_doc so tree recovery stays the same shape)

    def share_interners(self, other: "TensorTreeStore") -> None:
        """Alias ``other``'s interner tables (append-only) so handles in
        this store mean the same strings/values as in ``other`` — the
        precondition for ``other.adopt_doc`` copying our planes verbatim."""
        self._ids = other._ids
        self._fields = other._fields
        self._types = other._types
        self._values = other._values

    def clear_doc(self, row: int) -> None:
        """Reset one row to the empty tree (root only, overflow cleared)."""
        st = self.state
        fresh = TreeState.create(1, self.capacity)
        self.state = dataclasses.replace(
            st,
            **{k: getattr(st, k).at[row].set(getattr(fresh, k)[0])
               for k in _TREE_PLANES},
            overflow=st.overflow.at[row].set(0))

    def high_water(self, doc: int = 0) -> int:
        """1 + highest live slot index (root counts), for fit checks."""
        live = np.asarray(self.state.node_id[doc]) != 0
        return int(np.max(np.nonzero(live)[0])) + 1 if live.any() else 0

    def repack(self, doc: int = 0) -> None:
        """Compact a doc's live slots to the lowest indices. Slot position
        carries NO meaning in this representation (order/attachment are id
        handles — tree_kernel module docstring), so this is a pure
        permutation; it exists so a rebuilt doc whose history churned
        through many slots fits back into a small tier."""
        st = self.state
        p = {k: np.asarray(getattr(st, k)[doc]) for k in _TREE_PLANES}
        live = np.nonzero(p["node_id"] != 0)[0]
        updates = {}
        for k in _TREE_PLANES:
            row = np.zeros((self.capacity,), np.int32)
            row[:len(live)] = p[k][live]
            updates[k] = getattr(st, k).at[doc].set(jnp.asarray(row))
        self.state = dataclasses.replace(st, **updates)

    def adopt_doc(self, row: int, tmp: "TensorTreeStore") -> None:
        """Upload single-doc store ``tmp`` (which MUST share this store's
        interners — see ``share_interners``) into ``row``. Caller checks
        ``tmp.high_water() <= self.capacity`` first."""
        hw = tmp.high_water()
        assert hw <= self.capacity, "doc does not fit this tier"
        st = self.state
        updates = {}
        for k in _TREE_PLANES:
            src = np.zeros((self.capacity,), np.int32)
            src[:hw] = np.asarray(getattr(tmp.state, k)[0, :hw])
            updates[k] = getattr(st, k).at[row].set(jnp.asarray(src))
        self.state = dataclasses.replace(
            st, **updates, overflow=st.overflow.at[row].set(0))

    def digests(self) -> np.ndarray:
        return np.asarray(tree_state_digest(self.state))

    # ----------------------------------------------------- snapshot / resume

    def snapshot(self) -> dict:
        st = self.state
        return {
            "planes": {k: np.asarray(getattr(st, k)).copy()
                       for k in _TREE_PLANES},
            "overflow": np.asarray(st.overflow).copy(),
            "capacity": self.capacity,
            "ids": self._ids.export(),
            "fields": self._fields.export(),
            "types": self._types.export(),
            "values": self._values.export(),
        }

    def interner_bases(self) -> dict:
        """Append-only table lengths (incremental-summary baselines)."""
        return {"ids": len(self._ids), "fields": len(self._fields),
                "types": len(self._types), "values": len(self._values)}

    def snapshot_rows(self, rows, bases: dict) -> dict:
        """Incremental snapshot: only the given doc rows' planes (one
        fused device→host gather) plus the append-only interner DELTAS
        since the base summary (``bases`` = ``interner_bases()`` recorded
        then). Clean rows ride by reference to the base (SURVEY.md
        §2.16 handle reuse)."""
        from .schema import pad_rows_pow2
        rows = np.ascontiguousarray(rows, np.int32)
        if len(rows):
            rows_p, _p2, n = pad_rows_pow2(rows)
            g = gather_tree_rows_jit(self.state, jnp.asarray(rows_p))
            planes = {k: np.asarray(g[i])[:n].copy()
                      for i, k in enumerate(_TREE_PLANES)}
            overflow = np.asarray(g[-1])[:n].copy()
        else:
            planes = {k: np.zeros((0, self.capacity), np.int32)
                      for k in _TREE_PLANES}
            overflow = np.zeros((0,), np.int32)
        return {
            "rows": rows, "planes": planes, "overflow": overflow,
            "ids_delta": self._ids.export_from(bases["ids"]),
            "next_anon": self._ids._next_anon,
            "fields_delta": self._fields.export_from(bases["fields"]),
            "types_delta": self._types.export_from(bases["types"]),
            "values_delta": self._values.export_from(bases["values"]),
        }

    def apply_row_snapshot(self, delta: dict) -> None:
        """Fold one ``snapshot_rows`` delta into this (restored-base)
        store: overwrite the dirty rows' planes in one scatter, extend
        the append-only interner tables."""
        self._ids.extend_from(delta["ids_delta"])
        self._ids._next_anon = max(self._ids._next_anon,
                                   delta["next_anon"])
        self._fields.extend_from(delta["fields_delta"])
        self._types.extend_from(delta["types_delta"])
        self._values.extend_from(delta["values_delta"])
        from .schema import bucket_rows, pad_rows_pow2
        rows = np.asarray(delta["rows"], np.int32)
        if not len(rows):
            return
        rows_p, p2, n = pad_rows_pow2(rows)

        def bucket(a):
            return jnp.asarray(bucket_rows(a, p2, n))

        self.state = write_tree_rows_jit(
            self.state, jnp.asarray(rows_p),
            *(bucket(delta["planes"][k]) for k in _TREE_PLANES),
            bucket(delta["overflow"]))

    @classmethod
    def restore(cls, snap: dict, mesh=None) -> "TensorTreeStore":
        n_docs = snap["overflow"].shape[0]
        store = cls.__new__(cls)
        store.n_docs = n_docs
        store.capacity = snap["capacity"]
        store.mesh = mesh
        store.state = TreeState(
            **{k: jnp.asarray(snap["planes"][k]) for k in _TREE_PLANES},
            overflow=jnp.asarray(snap["overflow"]))
        if mesh is not None:
            from ..parallel.sharded import shard_tree_store_state
            store.state = shard_tree_store_state(store.state, mesh)
        store._ids = _Interner.restore(snap["ids"])
        store._fields = _Interner.restore(snap["fields"])
        store._types = _Interner.restore(snap["types"])
        store._values = ValueInterner.restore(snap["values"])
        store._wire_pool = {}
        store._map_pool = {}
        return store
