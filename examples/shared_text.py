"""Collaborative text editor example.

Reference counterpart: ``examples/data-objects/shared-text`` (+ the
ProseMirror integration that pairs SharedString with IntervalCollection) —
SURVEY.md §2.19, BASELINE configs #1/#5 (mount empty). The canonical Fluid
demo: a SharedString document with live co-editing, named comment ranges
(IntervalCollection over local references, sliding as text changes), title
metadata, and presence cursors over signals.

Run: ``PYTHONPATH=. python examples/shared_text.py`` — simulates a
three-author editing session over the in-process service and prints the
converged document. With ``--trace out.json`` the session's span trees
(outbox → wire → deli → serving apply → ack, one per op batch) are
exported as Chrome trace-event JSON and the first batch's tree is
printed via ``tools.trace_viewer``.
"""

from __future__ import annotations

import sys

from fluidframework_tpu.framework import LocalClient, PresenceManager

SCHEMA = {"initialObjects": {"text": "sharedString", "meta": "map"}}


class SharedTextSession:
    """One author's view of the document."""

    def __init__(self, container):
        self.container = container
        self.text = container.initial_objects["text"]
        self.meta = container.initial_objects["meta"]
        self.presence = PresenceManager(container.container)

    # editor operations
    def type_text(self, pos: int, s: str) -> None:
        self.text.insert_text(pos, s)
        self.presence.set_presence({"cursor": pos + len(s)})

    def delete(self, start: int, end: int) -> None:
        self.text.remove_text(start, end)
        self.presence.set_presence({"cursor": start})

    def comment(self, start: int, end: int, note: str) -> str:
        """Attach a comment to a range; the range slides with edits."""
        comments = self.text.get_interval_collection("comments")
        return comments.add(start, end, {"note": note})

    def comments(self):
        coll = self.text.get_interval_collection("comments")
        out = []
        for iv in coll.find_overlapping(0, self.text.get_length()):
            start, end = coll.endpoints(iv.interval_id)
            out.append((start, end, iv.props.get("note")))
        return out

    def set_title(self, title: str) -> None:
        self.meta.set("title", title)

    def format(self, start: int, end: int, **props) -> None:
        """Rich-text formatting: per-key LWW annotations (bold=True,
        color="red", key=None clears)."""
        self.text.annotate_range(start, end, props)

    def formatted_runs(self):
        """(text, props) runs of the document — what an editor renders.
        Walks segments directly: linear, and marker segments (which occupy
        a position but no text) stay correctly aligned."""
        runs = []
        tree = self.text.tree
        for seg in tree.segments:
            if not seg.text:
                continue  # markers occupy a position but render no text
            if seg.removed_seq is not None:
                continue  # removed (acked) or pending local remove
            props = {k: v for k, v in seg.props.items() if v is not None}
            if runs and runs[-1][1] == props:
                runs[-1] = (runs[-1][0] + seg.text, props)
            else:
                runs.append((seg.text, props))
        return runs


def main() -> int:
    client = LocalClient()
    c1, doc_id = client.create_container(SCHEMA)
    author1 = SharedTextSession(c1)
    author1.set_title("Design notes")
    author1.type_text(0, "Fluid merges concurrent edits.")

    author2 = SharedTextSession(client.get_container(doc_id, SCHEMA))
    author3 = SharedTextSession(client.get_container(doc_id, SCHEMA))

    # author2 comments on "concurrent edits", author3 prepends a heading —
    # the comment range must slide right as the heading lands
    cid = author2.comment(13, 29, "cite the merge-tree paper")
    author3.type_text(0, "# Notes\n")

    # concurrent typing at both ends
    author1.type_text(author1.text.get_length(), " All replicas converge.")
    author2.type_text(8, "INTRO: ")

    # rich-text formatting: author1 bolds the heading while author3 colors
    # "merges" — concurrent annotates on different keys both land; a later
    # annotate overwrites (per-key LWW)
    author1.format(0, 7, bold=True)
    author3.format(final_pos := author3.text.get_text().find("merges"),
                   final_pos + 6, color="red")
    author2.format(final_pos, final_pos + 6, color="blue")  # later wins

    texts = {a.text.get_text() for a in (author1, author2, author3)}
    assert len(texts) == 1, f"replicas diverged: {texts}"
    final = texts.pop()

    (start, end, note), = author3.comments()
    commented = final[start:end]

    print(f"doc_id   : {doc_id}")
    print(f"title    : {author3.meta.get('title')}")
    print(f"text     : {final!r}")
    print(f"comment  : {note!r} on {commented!r} [{start}:{end}]")
    runs = [(t, p) for t, p in author2.formatted_runs() if p]
    for t, p in runs:
        print(f"format   : {t!r} -> {p}")
    print(f"presence : {sorted(author1.presence.get_presences().values(), key=str)}")
    assert commented == "concurrent edits", commented
    assert ("# Notes", {"bold": True}) in [(t.rstrip('\n'), p) for t, p in runs]
    assert ("merges", {"color": "blue"}) in runs  # later annotate won
    assert all(a.formatted_runs() == author2.formatted_runs()
               for a in (author1, author3))
    print("converged: yes")

    if "--trace" in sys.argv:
        from fluidframework_tpu.tools import trace_viewer
        from fluidframework_tpu.utils import tracing
        path = sys.argv[sys.argv.index("--trace") + 1]
        tracing.TRACER.export_chrome(path)
        tids = tracing.TRACER.trace_ids()
        print(f"trace    : {len(tids)} trace(s) -> {path}")
        # show the first CLIENT batch (root = outbox.flush), not the
        # join-only service traces
        batch = [e["trace_id"] for e in tracing.TRACER.events()
                 if e["name"] == "outbox.flush"]
        if batch or tids:
            tid = batch[0] if batch else tids[0]
            print(trace_viewer.render(tracing.TRACER.events(tid)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
